"""Coordinator: launch + monitor worker processes across hosts.

Reference parity (``autodist/coordinator.py:46-110``): the chief re-runs
the *user's own script* on every other host with the serialized strategy
id in the environment, then fail-fast-monitors the remote processes
(``os._exit(1)`` when any worker dies). The TPU-native version keeps that
contract and adds the ``jax.distributed`` identity variables
(process id / process count / coordinator address) so the SPMD runtime
forms a single multi-host program instead of per-op RPC servers.

Remote execution is plain ssh via subprocess (paramiko-free: one less
dependency, same semantics); ``AUTODIST_DEBUG_REMOTE`` prints commands
instead of running them (reference cluster.py:340-342).
"""
import os
import shlex
import subprocess
import sys
import threading
import time

from autodist_tpu.const import (DEFAULT_COORD_PORT, DEFAULT_JAX_COORD_PORT,
                                DEFAULT_WORKING_DIR, ENV)
from autodist_tpu.utils import logging

_FORWARDED_FLAGS = (ENV.AUTODIST_MIN_LOG_LEVEL, ENV.AUTODIST_IS_TESTING,
                    ENV.AUTODIST_COORD_SERVICE_ADDR,
                    ENV.AUTODIST_HEARTBEAT_TIMEOUT,
                    ENV.AUTODIST_PS_ENDPOINTS, ENV.AUTODIST_PS_WIRE_DTYPE,
                    ENV.AUTODIST_PS_CHUNK_BYTES,
                    # row-sparse push knobs: every loose worker must
                    # classify deltas under the same threshold and
                    # refresh cadence, or the fleet's wire behavior
                    # (and its ps_stats audit) silently diverges
                    ENV.AUTODIST_SPARSE_PUSH_MAX_FRAC,
                    ENV.AUTODIST_SPARSE_FULL_REFRESH_EVERY,
                    # quantization block layout is part of the traced
                    # program (compressor) AND the PS frame format
                    ENV.AUTODIST_QUANT_BLOCK,
                    ENV.AUTODIST_S2D_STEM, ENV.AUTODIST_DENSENET_DUS,
                    # kernel-choice + pipeline-variant tracing flags:
                    # part of the traced program, and divergent HLO
                    # across SPMD hosts deadlocks
                    ENV.AUTODIST_FUSED_CONV,
                    ENV.AUTODIST_FUSED_CONV_MAX_ROWS,
                    ENV.AUTODIST_PP_STASH_LIMIT_MB,
                    # hierarchical node-group layout is part of the
                    # traced program (two-level collective schedules)
                    ENV.AUTODIST_HIERARCHY_NODES,
                    # weight-update-sharding override: the schedule and
                    # the optimizer-slot layout are part of the traced
                    # program — every SPMD host must agree
                    ENV.AUTODIST_WEIGHT_UPDATE_SHARDING,
                    # roofline observatory: every worker must account
                    # MFU on the same cadence against the same peak
                    # denominator or the cohort comparison skews
                    ENV.AUTODIST_ROOFLINE, ENV.AUTODIST_ROOFLINE_EVERY,
                    ENV.AUTODIST_ROOFLINE_PEAKS,
                    # bucket layout + overlap flags must agree on every
                    # traced host — divergent HLO across SPMD deadlocks
                    ENV.AUTODIST_BUCKET_BYTES, ENV.AUTODIST_XLA_OVERLAP,
                    ENV.AUTODIST_PS_TORN_RETRIES,
                    ENV.AUTODIST_PS_TORN_BACKOFF_S,
                    # async PS data-plane knobs: every loose-mode worker
                    # must agree on the pipeline depth and stall window
                    ENV.AUTODIST_PS_PIPELINE_DEPTH,
                    ENV.AUTODIST_PS_STALL_TIMEOUT_S,
                    # local-SGD window: the staleness gate counts sync
                    # ROUNDS under H>1, so every loose worker must agree
                    # on the window length (or the gates deadlock) and
                    # on the merge rule (or the merged state mixes
                    # scaled and unscaled deltas)
                    ENV.AUTODIST_LOCAL_STEPS,
                    ENV.AUTODIST_LOCAL_SGD_AVERAGE,
                    # elastic recovery: every worker must judge peer
                    # failures under the same policy and bounds
                    ENV.AUTODIST_PEER_FAILURE_POLICY,
                    ENV.AUTODIST_MIN_WORKERS,
                    ENV.AUTODIST_MAX_WORKER_RESTARTS,
                    ENV.AUTODIST_RESTART_WAIT_S,
                    # elastic scale-up: every worker judges the join
                    # ceiling identically (a joiner enforces it at its
                    # own admit claim)
                    ENV.AUTODIST_MAX_WORKERS,
                    # telemetry plane: a cohort timeline needs every
                    # worker emitting (and bounding buffers / pushing
                    # batches / sizing the flight-recorder ring) under
                    # the same knobs as the chief
                    ENV.AUTODIST_TELEMETRY,
                    ENV.AUTODIST_TELEMETRY_DIR,
                    ENV.AUTODIST_TELEMETRY_MAX_SPANS,
                    ENV.AUTODIST_TELEMETRY_PUSH_EVERY,
                    ENV.AUTODIST_FLIGHT_RECORDER_EVENTS,
                    # serving tier: launched replicas must grade
                    # staleness against the same bound, poll on the
                    # same cadence and pull on the same wire as the
                    # fleet that autoscaled them, or the serve_stats
                    # the AutoscaleController reads mix regimes
                    ENV.AUTODIST_SERVE_POLL_S,
                    ENV.AUTODIST_SERVE_STALENESS_BOUND,
                    ENV.AUTODIST_SERVE_ROW_CACHE_ROWS,
                    ENV.AUTODIST_SERVE_ROW_TTL_S,
                    ENV.AUTODIST_SERVE_SNAPSHOT_RETRIES,
                    ENV.AUTODIST_SERVE_WIRE,
                    # epoch-swap handshake: the replan opt-in and the
                    # handshake bounds are cohort-wide — every member
                    # must validate/ack staged plans and apply at the
                    # armed boundary, and peers bound their ready-
                    # marker wait with the same ack timeout
                    ENV.AUTODIST_EXECUTE_REPLAN,
                    ENV.AUTODIST_SWAP_ACK_TIMEOUT_S,
                    ENV.AUTODIST_SWAP_RETRY_BACKOFF_S,
                    ENV.AUTODIST_SWAP_MAX_RETRIES,
                    ENV.SYS_DATA_PATH, ENV.SYS_RESOURCE_PATH)


class WorkerSupervisor:
    """Policy-aware babysitter for ONE worker process — the recovery
    half of the reference's fail-fast monitor (coordinator.py:98-110).

    - ``fail`` (default): any nonzero exit calls ``on_give_up`` (the
      chief aborts) — the pre-recovery behavior.
    - ``exclude``: a dead worker is logged and left to the surviving
      peers, which fence its generation and shrink the gate membership.
    - ``restart``: up to ``max_restarts`` supervised respawns with
      capped exponential backoff; the dead incarnation's writer
      generation is fenced (``fence`` callback) BEFORE every respawn —
      an ssh-severed zombie may still be alive on the remote host, and
      its writes must be rejected from the moment its replacement can
      exist. A fence attempt that fails consumes one restart attempt
      and is retried under the backoff (never an unfenced respawn, but
      never a whole-chief abort on one transient RPC miss either).
      Exhausting the cap runs ``mark_failed`` (so blocked peers
      stop waiting) and then gives up.

    ``spawn``/``fence``/``mark_failed``/``on_give_up``/``sleep`` are
    injectable so the supervision loop is unit-testable without ssh.
    """

    def __init__(self, address, spawn, policy='fail', max_restarts=0,
                 fence=None, mark_failed=None, on_give_up=None,
                 is_shutting_down=None, backoff_base_s=0.5,
                 backoff_cap_s=30.0, sleep=time.sleep):
        self.address = address
        self.proc = None
        self.restarts = 0
        self._spawn = spawn
        self._policy = policy
        self._max_restarts = max_restarts
        self._fence = fence
        self._mark_failed = mark_failed
        self._on_give_up = on_give_up or (lambda code: None)
        self._is_shutting_down = is_shutting_down or (lambda: False)
        self._backoff_base_s = backoff_base_s
        self._backoff_cap_s = backoff_cap_s
        self._sleep = sleep
        self._thread = None
        # serializes respawn against terminate(): either the respawn
        # sees the shutdown flag inside the lock, or terminate() sees
        # (and kills) the freshly assigned proc — a terminate landing
        # between the shutdown check and the Popen cannot orphan a
        # respawned worker nobody will ever stop
        self._spawn_lock = threading.Lock()

    def backoff_s(self, attempt):
        """Backoff before restart ``attempt`` (1-based): exponential
        from the base, capped."""
        return min(self._backoff_cap_s,
                   self._backoff_base_s * (2.0 ** (attempt - 1)))

    def start(self):
        self.proc = self._spawn()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name='autodist-supervise-%s' % self.address)
        self._thread.start()
        return self

    def _run(self):
        while True:
            code = self.proc.wait()
            if code == 0 or self._is_shutting_down():
                return
            if self._policy == 'exclude':
                logging.warning(
                    'Worker %s exited with code %s; policy=exclude '
                    'leaves recovery to the surviving peers (they '
                    'fence its generation and shrink the gate '
                    'membership)', self.address, code)
                return
            if self._policy == 'restart' and \
                    self.restarts < self._max_restarts:
                self.restarts += 1
                delay = self.backoff_s(self.restarts)
                logging.warning(
                    'Worker %s exited with code %s; supervised restart '
                    '%d/%d in %.1fs', self.address, code,
                    self.restarts, self._max_restarts, delay)
                self._sleep(delay)
                # a shutdown that began during the backoff (Ctrl-C,
                # clean teardown) must not be followed by a respawn
                # nobody will ever terminate — and a fence failure
                # against an already-torn-down coord service is not a
                # reason to hard-abort the chief
                if self._is_shutting_down():
                    return
                try:
                    if self._fence is not None:
                        self._fence()
                except Exception as e:  # noqa: BLE001 - retried below
                    if self._is_shutting_down():
                        return
                    # an unfenced respawn is still refused — but a
                    # transient fence failure (network blip to one PS
                    # endpoint, the dead worker's co-hosted endpoint
                    # rebooting) burns ONE restart attempt and retries
                    # under the growing backoff instead of hard-killing
                    # the whole chief on the first miss
                    logging.warning(
                        'cannot fence dead worker %s (%s: %s); '
                        'refusing an unfenced respawn — retrying the '
                        'fence (attempt %d/%d)', self.address,
                        type(e).__name__, e, self.restarts,
                        self._max_restarts)
                    continue
                try:
                    with self._spawn_lock:
                        if self._is_shutting_down():
                            return
                        self.proc = self._spawn()
                    from autodist_tpu import telemetry as _telemetry
                    _telemetry.recorder().record(
                        'worker_respawn', address=str(self.address),
                        attempt=self.restarts)
                except Exception as e:  # noqa: BLE001 - abort below
                    logging.error('respawn of worker %s failed: %s: %s',
                                  self.address, type(e).__name__, e)
                    self._on_give_up(code)
                    return
                continue
            if self._policy == 'restart':
                logging.error(
                    'Worker %s exhausted %d supervised restarts; '
                    'marking it permanently failed', self.address,
                    self._max_restarts)
                try:
                    if self._mark_failed is not None:
                        self._mark_failed()
                except Exception as e:  # noqa: BLE001 - best effort
                    logging.warning(
                        'could not mark worker %s failed on the coord '
                        'service: %s: %s', self.address,
                        type(e).__name__, e)
            else:
                logging.error(
                    'Worker %s exited with code %s; aborting chief',
                    self.address, code)
            self._on_give_up(code)
            return

    def join(self, timeout=None):
        if self._thread is not None:
            self._thread.join(timeout)

    def terminate(self):
        with self._spawn_lock:
            if self.proc is not None and self.proc.poll() is None:
                self.proc.terminate()


def autoscale_policy(step_time_target_s=None, queue_depth_max=None,
                     grow_by=1):
    """The built-in autoscale policy: grow when the observed per-step
    wall time exceeds ``step_time_target_s`` or the input queue depth
    exceeds ``queue_depth_max`` (either signal suffices; unset signals
    are ignored). Returns a policy callable
    ``policy(metrics, current_world) -> desired world | None`` for
    :class:`AutoscaleController` — ``None`` means "no opinion, keep
    the current size".

    The policy may assume: ``metrics`` is a plain dict sampled by the
    caller (``step_time_s``, ``queue_depth`` — both optional), and the
    returned size is a TARGET the controller clamps and executes. It
    may NOT assume its decision is applied (``AUTODIST_MAX_WORKERS``
    caps it, scale-down is recorded-but-unsupported) or that admitted
    capacity arrives synchronously (a joiner takes an admit handshake
    plus an XLA compile to contribute).
    """
    def policy(metrics, current_world):
        step_s = metrics.get('step_time_s')
        depth = metrics.get('queue_depth')
        if step_time_target_s is not None and step_s is not None \
                and step_s > step_time_target_s:
            return current_world + grow_by
        if queue_depth_max is not None and depth is not None \
                and depth > queue_depth_max:
            return current_world + grow_by
        return None
    return policy


class AutoscaleController:
    """The injectable autoscale policy hook (elastic scale-up's
    decision layer): each :meth:`tick` samples caller-provided metrics,
    asks the ``policy`` for a desired world size, clamps it to
    ``AUTODIST_MAX_WORKERS`` and executes growth through the injected
    ``scale_up`` callable (``Coordinator.scale_up`` in production, a
    recorder in tests). Every decision — taken, skipped, capped or
    failed — is recorded on :attr:`decisions` so
    ``profiling.health_report`` can audit the autoscaler alongside the
    recovery machinery.

    Scale-DOWN is recorded as skipped, not executed: membership only
    grows (the world counter is monotone); shrinking rides the
    exclude-policy path when a worker actually leaves.
    """

    def __init__(self, policy, scale_up, current_world,
                 max_workers=None, live_world=None,
                 metrics_source=None):
        self._policy = policy
        self._scale_up = scale_up
        self.world = current_world
        self._max = max_workers if max_workers is not None \
            else ENV.AUTODIST_MAX_WORKERS.val
        # optional zero-arg callable returning live membership: each
        # tick resyncs from it, so deaths hand their headroom back —
        # a local-only world at the cap would otherwise skip forever
        # after churn, and a launched-but-refused joiner would count
        # as phantom capacity permanently
        self._live_world = live_world
        # optional zero-arg callable returning sampled metrics merged
        # under each tick's explicit metrics (explicit wins). The
        # production wiring is the chief's CohortMonitor.metrics —
        # that is what puts a COMPUTED step_time_s behind the built-in
        # policy's step_time_target_s signal instead of a stub the
        # caller had to fabricate.
        self._metrics_source = metrics_source
        self.decisions = []

    @property
    def taken(self):
        return sum(1 for d in self.decisions
                   if d['action'] == 'scale_up')

    @property
    def skipped(self):
        """Deliberate skips only — a FAILED scale-up is an
        infrastructure error, not a policy decision, and the audit
        trail must not launder one into the other."""
        return sum(1 for d in self.decisions
                   if d['action'] == 'skipped')

    @property
    def failed(self):
        return sum(1 for d in self.decisions
                   if d['action'] == 'failed')

    def tick(self, metrics=None):
        """One autoscale evaluation; returns the decision record.
        ``metrics`` (optional) overlays the ``metrics_source`` sample —
        callers can still force a signal for a single tick."""
        explicit = dict(metrics or {})
        metrics = {}
        if self._metrics_source is not None:
            try:
                metrics = dict(self._metrics_source() or {})
            except Exception as e:  # noqa: BLE001 - the sampled
                # signal is advisory; a monitor hiccup must not kill
                # the autoscale loop
                logging.warning('autoscale metrics_source failed: '
                                '%s: %s', type(e).__name__, e)
        metrics.update(explicit)
        if self._live_world is not None:
            try:
                live = self._live_world()
                if live:
                    self.world = live
            except Exception as e:  # noqa: BLE001 - resync is advisory
                logging.warning('autoscale live-world resync failed: '
                                '%s: %s', type(e).__name__, e)
        desired = self._policy(metrics, self.world)
        rec = {'world': self.world, 'metrics': metrics,
               'desired': desired}
        if desired is None or desired == self.world:
            rec.update(action='skipped',
                       reason='no_opinion' if desired is None
                       else 'at_target')
        elif desired < self.world:
            rec.update(action='skipped',
                       reason='scale_down_unsupported')
        else:
            granted = min(desired, self._max)
            if granted <= self.world:
                rec.update(action='skipped',
                           reason='AUTODIST_MAX_WORKERS')
            else:
                try:
                    asked = granted - self.world
                    got = self._scale_up(asked)
                    # believe what was actually LAUNCHED, not what was
                    # asked: Coordinator.scale_up clamps against its
                    # own live-membership room (possibly to zero) and
                    # returns the supervisors it started — advancing
                    # `world` past reality would make the controller
                    # see phantom capacity and never fire again.
                    # Contract: scale_up returns the launched
                    # supervisors (list) or a count; a bare-None
                    # return (a void callable) is trusted as fully
                    # launched — pair such a callable with live_world
                    # so reality resyncs each tick.
                    launched = len(got) if isinstance(
                        got, (list, tuple)) else (
                        got if isinstance(got, int) else asked)
                    if launched <= 0:
                        rec.update(action='skipped',
                                   reason='scale_up_launched_nothing')
                    else:
                        self.world += launched
                        rec.update(action='scale_up',
                                   granted=self.world,
                                   launched=launched)
                except Exception as e:  # noqa: BLE001 - recorded, the
                    # autoscaler advising must not kill the run
                    rec.update(action='failed',
                               error='%s: %s' % (type(e).__name__, e))
                    logging.warning('autoscale scale_up to %d failed: '
                                    '%s', granted, rec['error'])
        self.decisions.append(rec)
        from autodist_tpu import telemetry as _telemetry
        if rec['action'] != 'skipped':
            # only decisions that DID something (or failed trying)
            # enter the bounded crash ring — a per-step no-op tick
            # would otherwise scroll the post-mortem window the
            # flight recorder exists to preserve
            _telemetry.recorder().record(
                'autoscale', action=rec['action'],
                reason=rec.get('reason', ''), world=rec['world'],
                desired=desired)
        _telemetry.get().count('autoscale/%s' % rec['action'])
        if rec['action'] == 'scale_up':
            logging.info('autoscale: world %d -> %d (%s)',
                         rec['world'], rec['granted'], metrics)
        return rec


# AUTODIST_COORD_TOKEN is deliberately NOT in _FORWARDED_FLAGS: env
# assignments ride the remote ssh command line, which is world-readable
# in `ps` on the worker host. The secret ships as a mode-0600 file
# instead (_copy_token), referenced via AUTODIST_COORD_TOKEN_FILE.


class Coordinator:
    """Launch the current program on every worker host and babysit it."""

    def __init__(self, strategy, resource_spec, cluster=None):
        self._strategy = strategy
        self._resource_spec = resource_spec
        self._cluster = cluster
        self._shutting_down = False
        self.supervisors = []
        self._token_path = ''
        # arm the XLA overlap flags BEFORE building worker envs: any
        # AllReduce node means bucketed gradient sync, and the flags
        # must reach workers at process start (their backend init)
        from autodist_tpu.strategy.base import AllReduceSynchronizer
        has_ar = any(
            isinstance(s, AllReduceSynchronizer)
            for node in strategy.node_config
            for s in [node.synchronizer] + list(node.part_config)
            if s is not None)
        if has_ar:
            from autodist_tpu.utils.jax_env import setup_overlap_flags
            applied = setup_overlap_flags()
            if applied:
                logging.info('Armed XLA overlap flags for bucketed '
                             'gradient sync: %s', applied)

    def _worker_env(self, worker_addr, process_id):
        env = {
            ENV.AUTODIST_WORKER.name: worker_addr,
            ENV.AUTODIST_STRATEGY_ID.name: self._strategy.id,
            ENV.AUTODIST_PROCESS_ID.name: str(process_id),
            ENV.AUTODIST_NUM_PROCESSES.name:
                os.environ.get(ENV.AUTODIST_NUM_PROCESSES.name) or
                str(len(list(self._resource_spec.nodes))),
            ENV.AUTODIST_COORDINATOR_ADDR.name:
                ENV.AUTODIST_COORDINATOR_ADDR.val or
                ('%s:%d' % (self._resource_spec.chief,
                            DEFAULT_JAX_COORD_PORT)),
            ENV.AUTODIST_COORD_SERVICE_ADDR.name:
                ENV.AUTODIST_COORD_SERVICE_ADDR.val or
                ('%s:%d' % (self._resource_spec.chief,
                            DEFAULT_COORD_PORT)),
        }
        for flag in _FORWARDED_FLAGS:
            raw = os.environ.get(flag.name)
            if raw:
                env[flag.name] = raw
        # libtpu reads this once at backend init: forwarding it lets the
        # overlap flags armed on the chief (utils/jax_env.py
        # setup_overlap_flags) take effect from worker process start
        raw = os.environ.get('LIBTPU_INIT_ARGS')
        if raw:
            env['LIBTPU_INIT_ARGS'] = raw
        if self._token_path:
            env[ENV.AUTODIST_COORD_TOKEN_FILE.name] = self._token_path
        return env

    def _ssh_base(self, ssh_config, scp=False):
        cmd = ['scp' if scp else 'ssh', '-o',
               'StrictHostKeyChecking=no']
        if ssh_config and ssh_config.key_file:
            cmd += ['-i', ssh_config.key_file]
        if ssh_config and ssh_config.port != 22:
            cmd += ['-P' if scp else '-p', str(ssh_config.port)]
        return cmd

    @staticmethod
    def _target(address, ssh_config):
        return address if not (ssh_config and ssh_config.username) \
            else '%s@%s' % (ssh_config.username, address)

    @staticmethod
    def _run_remote(cmd, what, timeout_s=60.0, retries=1,
                    retry_wait_s=1.0):
        """Run one ssh/scp shipping command with a timeout and a single
        retried attempt: a transient SSH hiccup (dropped handshake,
        momentary DNS stall) must not abort the whole multi-host
        launch, and a wedged transfer must not hang it forever."""
        for attempt in range(retries + 1):
            try:
                subprocess.run(cmd, check=True, timeout=timeout_s)
                return
            except (subprocess.SubprocessError, OSError) as e:
                if attempt >= retries:
                    raise
                logging.warning('%s failed (%s: %s); retrying in %.0fs',
                                what, type(e).__name__, e, retry_wait_s)
                time.sleep(retry_wait_s)

    def _copy_strategy(self, address, ssh_config):
        """Ship the serialized strategy file to a worker host (reference
        coordinator.py:56-64 SFTP copy).

        Copies to a temp name then renames remotely: atomic placement,
        and safe when chief and worker share a filesystem (scp'ing a
        file onto its own path truncates it before reading)."""
        src = self._strategy.path
        tmp = '%s.ship.%d' % (src, os.getpid())
        target = self._target(address, ssh_config)
        scp_cmd = self._ssh_base(ssh_config, scp=True) + \
            [src, '%s:%s' % (target, tmp)]
        mv_cmd = self._ssh_base(ssh_config) + \
            [target, 'mv -f %s %s' % (shlex.quote(tmp), shlex.quote(src))]
        if ENV.AUTODIST_DEBUG_REMOTE.val:
            logging.info('[debug-remote] %s', ' '.join(scp_cmd))
            logging.info('[debug-remote] %s', ' '.join(mv_cmd))
            return
        self._run_remote(scp_cmd, 'strategy scp to %s' % address)
        self._run_remote(mv_cmd, 'strategy rename on %s' % address)

    def _copy_token(self, address, ssh_config):
        """Ship the coord-service shared secret to a worker host as a
        mode-0600 file (env assignments ride the remote command line —
        world-readable in `ps` — so the secret goes by file, like the
        reference rode authenticated scp for everything it shipped)."""
        from autodist_tpu.runtime.coord_client import coord_token
        token = coord_token()
        if not token:
            self._token_path = ''
            return
        path = os.path.join(os.path.dirname(self._strategy.path),
                            'coord_token')
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, 'w') as f:
            f.write(token)
        self._token_path = path
        tmp = '%s.ship.%d' % (path, os.getpid())
        target = self._target(address, ssh_config)
        scp_cmd = self._ssh_base(ssh_config, scp=True) + \
            [path, '%s:%s' % (target, tmp)]
        mv_cmd = self._ssh_base(ssh_config) + \
            [target, 'chmod 600 %s && mv -f %s %s' %
             (shlex.quote(tmp), shlex.quote(tmp), shlex.quote(path))]
        if ENV.AUTODIST_DEBUG_REMOTE.val:
            logging.info('[debug-remote] %s', ' '.join(scp_cmd))
            logging.info('[debug-remote] %s', ' '.join(mv_cmd))
            return
        self._run_remote(scp_cmd, 'coord token scp to %s' % address)
        self._run_remote(mv_cmd, 'coord token chmod+rename on %s'
                         % address)

    @property
    def procs(self):
        """Live worker processes (the current incarnation under each
        supervisor — restarts swap the entries in place)."""
        return [s.proc for s in self.supervisors if s.proc is not None]

    def _coord_service_targets(self):
        """Every service holding fence counters: the coord service plus
        each PS endpoint (each keeps its OWN counter map, so a fence
        bump must land on all of them). Local spellings are normalized
        ('localhost' and friends -> 127.0.0.1) BEFORE the dedup: one
        service named two ways would otherwise get a DOUBLE generation
        bump per death, skewing its counter ahead of the generation the
        replacement reads from the coord service — a later zombie's
        writes would then pass that service's fence check."""
        from autodist_tpu.runtime.cluster import is_local_address
        from autodist_tpu.runtime.coord_client import ps_endpoints
        addr = ENV.AUTODIST_COORD_SERVICE_ADDR.val or \
            '%s:%d' % (self._resource_spec.chief, DEFAULT_COORD_PORT)
        host, port = addr.rsplit(':', 1)

        def norm(h, p):
            return ('127.0.0.1' if is_local_address(h) else h, int(p))

        targets = [norm(host, port)]
        for h, p in ps_endpoints():
            ep = norm(h, p)
            if ep not in targets:
                targets.append(ep)
        return targets

    def _fence_worker(self, process_id):
        """Bump the dead worker's fencing generation everywhere it
        could write; its replacement reads the new generation at
        session init and joins under it."""
        from autodist_tpu.runtime import coord_client as cc
        # fence counters live OUTSIDE the run namespace (see
        # Session._exclude_peer): they must survive the run-end purge
        key = 'fence/%s/p%d' % (self._strategy.id, process_id)
        for host, port in self._coord_service_targets():
            client = cc.connect_with_retry((host, port), deadline_s=15.0)
            try:
                gen = client.incr(key, 1)
            finally:
                client.close()
        logging.info('fenced dead worker p%d at generation %d',
                     process_id, gen)

    def _mark_worker_failed(self, process_id):
        """Record permanent failure (restart budget exhausted) so peers
        blocked on the staleness gate stop waiting and raise."""
        from autodist_tpu.runtime import coord_client as cc
        host, port = self._coord_service_targets()[0]
        client = cc.connect_with_retry((host, port), deadline_s=15.0)
        try:
            client.set('%s/failed/p%d' % (self._strategy.id,
                                          process_id), '1')
        finally:
            client.close()

    @staticmethod
    def _abort_chief(code):
        os._exit(1)

    def _effective_policy(self):
        """The peer-failure policy workers are supervised under.
        ``exclude``/``restart`` recovery lives in the loose-mode PS
        plane (heartbeats + staleness gate + fenced rejoin); an SPMD
        run has none of it — survivors would block in jax collectives
        forever while the supervisor "leaves recovery to the peers" —
        so a non-loose strategy keeps the fail-fast guarantee."""
        policy = ENV.AUTODIST_PEER_FAILURE_POLICY.val
        if policy == 'fail':
            return policy
        from autodist_tpu.autodist import AutoDist
        if AutoDist._strategy_is_loose(self._strategy):
            return policy
        logging.warning(
            'AUTODIST_PEER_FAILURE_POLICY=%s only applies to relaxed-'
            'consistency (loose-mode) PS strategies; this strategy '
            'runs SPMD, where a lost worker cannot be excluded or '
            'rejoined — supervising workers under the fail policy '
            'instead', policy)
        return 'fail'

    def _launch_supervised(self, address, pid, policy, extra_env=None):
        """Ship prerequisites to ``address`` and start ONE worker
        process there (process id ``pid``) under a policy-aware
        :class:`WorkerSupervisor`. Returns the supervisor (None in
        debug-remote mode)."""
        script = ' '.join(shlex.quote(a) for a in
                          [sys.executable] + sys.argv)
        max_restarts = ENV.AUTODIST_MAX_WORKER_RESTARTS.val
        ssh_config = self._resource_spec.ssh_config(address)
        self._copy_strategy(address, ssh_config)
        self._copy_token(address, ssh_config)
        env = self._worker_env(address, pid)
        if extra_env:
            env.update(extra_env)
        env_str = ' '.join('%s=%s' % (k, shlex.quote(v))
                           for k, v in env.items())
        venv = ''
        if ssh_config and ssh_config.python_venv:
            venv = '. %s/bin/activate && ' % ssh_config.python_venv
        remote_cmd = 'cd %s && %s%s %s' % (
            shlex.quote(os.getcwd()), venv, env_str, script)
        cmd = self._ssh_base(ssh_config) + \
            [self._target(address, ssh_config), remote_cmd]
        if ENV.AUTODIST_DEBUG_REMOTE.val:
            logging.info('[debug-remote] %s', ' '.join(cmd))
            return None

        def spawn(cmd=cmd, address=address):
            logging.info('Launching worker on %s', address)
            return subprocess.Popen(cmd)

        sup = WorkerSupervisor(
            address, spawn, policy=policy,
            max_restarts=max_restarts,
            fence=lambda pid=pid: self._fence_worker(pid),
            mark_failed=lambda pid=pid: self._mark_worker_failed(pid),
            on_give_up=self._abort_chief,
            is_shutting_down=lambda: self._shutting_down).start()
        self.supervisors.append(sup)
        from autodist_tpu import telemetry as _telemetry
        _telemetry.recorder().record(
            'worker_launch', worker='p%d' % pid, address=str(address),
            policy=policy,
            elastic_join=bool(extra_env and
                              ENV.AUTODIST_ELASTIC_JOIN.name
                              in extra_env))
        return sup

    def launch_clients(self):
        """Re-run ``sys.argv`` on every non-chief replica host, each
        under a policy-aware :class:`WorkerSupervisor`."""
        chief = self._resource_spec.chief
        workers = [n for n in self._resource_spec.nodes if n != chief]
        policy = self._effective_policy()
        for i, address in enumerate(workers, start=1):
            self._launch_supervised(address, i, policy)
        self._next_pid = len(workers) + 1
        return self

    def scale_up(self, count, addresses=None):
        """Launch ``count`` ADDITIONAL workers into the RUNNING job —
        the supervised half of elastic scale-up. Each new process
        carries ``AUTODIST_ELASTIC_JOIN=1`` and admits itself at the
        control plane (:func:`autodist_tpu.runtime.session.admit_worker`
        claims its definitive worker slot there; the env process id is
        advisory). ``addresses`` defaults to cycling the spec's nodes
        (non-chief first), matching the reference's one-worker-per-host
        layout while still allowing same-host growth.

        Capped by ``AUTODIST_MAX_WORKERS`` against the pids this
        coordinator has issued; the joiner's own admit claim enforces
        the ceiling against the live world (a claim raced past the cap
        is retired as excluded, so live membership never exceeds it).

        Supervision policy: a scale-up worker is supervised under
        ``exclude`` semantics whenever recovery is enabled — a dead
        joiner's SLOT is excluded by the surviving peers and any
        replacement re-JOINs as a fresh slot; re-binding the dead slot
        (the ``restart`` path) would leave survivors waiting on a
        counter no replacement will ever advance, because the monotone
        world counter never re-issues ordinals. ``fail`` stays
        fail-fast. Returns the new supervisors.
        """
        policy = self._effective_policy()
        if policy == 'restart':
            logging.info('scale-up workers are supervised under '
                         'exclude semantics (a dead joiner re-admits '
                         'as a fresh slot; its old slot is excluded '
                         'by the peers)')
            policy = 'exclude'
        max_workers = ENV.AUTODIST_MAX_WORKERS.val
        next_pid = getattr(self, '_next_pid',
                           len(list(self._resource_spec.nodes)))
        room = max(0, max_workers - self._live_world_estimate(next_pid))
        if count > room:
            logging.warning(
                'scale_up(%d) clamped to %d: AUTODIST_MAX_WORKERS=%d '
                'bounds the LIVE membership', count, room, max_workers)
            count = room
        if addresses is None:
            chief = self._resource_spec.chief
            nodes = list(self._resource_spec.nodes)
            pool = [n for n in nodes if n != chief] or nodes
            addresses = [pool[i % len(pool)] for i in range(count)]
        new = []
        for address in addresses[:count]:
            pid = next_pid
            next_pid += 1
            sup = self._launch_supervised(
                address, pid, policy,
                extra_env={ENV.AUTODIST_ELASTIC_JOIN.name: '1'})
            if sup is not None:
                new.append(sup)
        self._next_pid = next_pid
        return new

    def _live_world_estimate(self, fallback):
        """Live membership (claimed ordinals minus excluded) read from
        the coord service, so exclusions hand their cap headroom back
        — a churny long-running job must not ratchet itself below the
        ceiling it is allowed to refill. Falls back to the issued-pid
        count when the service is unreachable (the joiner's own admit
        claim enforces the ceiling authoritatively either way)."""
        from autodist_tpu.runtime import coord_client as cc
        from autodist_tpu.runtime.session import live_members_on_plane
        try:
            host, port = self._coord_service_targets()[0]
            client = cc.CoordClient((host, port), timeout=2.0)
            try:
                live, world, _ = live_members_on_plane(
                    client, self._strategy.id)
                return live if world > 0 else fallback
            finally:
                client.close()
        except OSError:
            return fallback

    def autoscaler(self, policy, metrics_source=None):
        """An :class:`AutoscaleController` wired to this coordinator:
        its decisions execute through :meth:`scale_up`, starting from
        the worker ordinals this coordinator has already issued (NOT
        the launch node count — a manual ``scale_up`` call before the
        controller exists must not read as phantom headroom).
        ``metrics_source`` feeds each tick's sampled metrics — pass
        the chief session's ``monitor.metrics`` so the built-in
        ``step_time_target_s`` policy runs on the cohort's measured
        step time instead of caller-fabricated numbers."""
        fallback = getattr(self, '_next_pid',
                           len(list(self._resource_spec.nodes)))
        return AutoscaleController(
            policy, self.scale_up, current_world=fallback,
            live_world=lambda: self._live_world_estimate(
                getattr(self, '_next_pid', fallback)),
            metrics_source=metrics_source)

    def join(self):
        for s in self.supervisors:
            s.join()

    def terminate(self):
        self._shutting_down = True
        for s in self.supervisors:
            s.terminate()


def launch_cli(argv=None):
    """``python -m autodist_tpu.launch [--spec r.yml] script.py args...``

    The pod-native launcher: starts one process per host entry of the
    resource spec (locally via subprocess, remotely via ssh) with the
    jax.distributed identity env set — the same-binary-everywhere model
    of TPU pods, while the Coordinator covers the reference's
    chief-re-runs-your-script model.
    """
    import argparse
    parser = argparse.ArgumentParser(prog='autodist_tpu.launch')
    parser.add_argument('--spec', help='resource spec YAML',
                        default=ENV.SYS_RESOURCE_PATH.val or None)
    parser.add_argument('--coordinator-port', type=int,
                        default=DEFAULT_JAX_COORD_PORT)
    parser.add_argument('script')
    parser.add_argument('args', nargs=argparse.REMAINDER)
    ns = parser.parse_args(argv)

    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.runtime.cluster import is_local_address
    spec = ResourceSpec(resource_file=ns.spec) if ns.spec else None
    nodes = list(spec.nodes) if spec else ['localhost']
    chief = spec.chief if spec else 'localhost'
    nodes = [chief] + [n for n in nodes if n != chief]
    coord = '%s:%d' % (chief, ns.coordinator_port)
    coord_service = ENV.AUTODIST_COORD_SERVICE_ADDR.val or \
        '%s:%d' % (chief, DEFAULT_COORD_PORT)

    os.makedirs(DEFAULT_WORKING_DIR, exist_ok=True)
    # The launcher owns the coord service (and any local PS endpoint
    # services): they must outlive every process (a fast chief may
    # finish while slow workers still push PS deltas).
    service_procs = []
    cs_host, cs_port = coord_service.rsplit(':', 1)
    if is_local_address(cs_host):
        from autodist_tpu.runtime import coord_client
        all_local = all(is_local_address(n) for n in nodes)
        service_procs.append(coord_client.ensure_service(
            int(cs_port), bind='127.0.0.1' if all_local else '0.0.0.0'))
        if all_local:
            # bound to loopback -> children must connect via loopback,
            # even when the spec names this host by its NIC IP
            coord_service = '127.0.0.1:%s' % cs_port
        for ep_host, ep_port in coord_client.ps_endpoints():
            if is_local_address(ep_host):
                service_procs.append(coord_client.ensure_service(
                    ep_port, bind='127.0.0.1' if all_local else '0.0.0.0'))
    import uuid
    run_id = uuid.uuid4().hex[:12]
    procs = []
    for i, address in enumerate(nodes):
        env = dict(os.environ)
        env.update({
            ENV.AUTODIST_PROCESS_ID.name: str(i),
            ENV.AUTODIST_NUM_PROCESSES.name: str(len(nodes)),
            ENV.AUTODIST_COORDINATOR_ADDR.name: coord,
            ENV.AUTODIST_COORD_SERVICE_ADDR.name: coord_service,
            ENV.AUTODIST_RUN_ID.name: run_id,
        })
        if i > 0:
            env[ENV.AUTODIST_WORKER.name] = address
        cmd = [sys.executable, ns.script] + ns.args
        if is_local_address(address):
            # same-host process (multi-process-per-host and test tiers)
            procs.append(subprocess.Popen(cmd, env=env))
        else:
            ssh_config = spec.ssh_config(address) if spec else None
            env_flags = {k: env[k] for k in env
                         if k.startswith('AUTODIST_')}
            env_str = ' '.join('%s=%s' % (k, shlex.quote(v))
                               for k, v in env_flags.items())
            remote = 'cd %s && %s %s' % (
                shlex.quote(os.getcwd()), env_str,
                ' '.join(shlex.quote(a) for a in cmd))
            ssh_cmd = ['ssh', '-o', 'StrictHostKeyChecking=no']
            if ssh_config and ssh_config.key_file:
                ssh_cmd += ['-i', ssh_config.key_file]
            target = address if not (ssh_config and ssh_config.username) \
                else '%s@%s' % (ssh_config.username, address)
            ssh_cmd += [target, remote]
            if ENV.AUTODIST_DEBUG_REMOTE.val:
                logging.info('[debug-remote] %s', ' '.join(ssh_cmd))
                continue
            procs.append(subprocess.Popen(ssh_cmd, env=env))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    for sp in service_procs:
        if sp is not None:
            sp.terminate()
    return rc
