"""runtime subpackage."""
