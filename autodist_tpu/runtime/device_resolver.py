"""Abstract -> concrete device resolution.

The reference's ``DeviceResolver`` (``autodist/kernel/device/resolver.py:
47-67``) maps AutoDist device strings ``ip:GPU:0`` to TF device strings
``/job:worker/task:i/device:GPU:0`` via the cluster spec, so strategy
placement decisions become executable addresses. The TPU-native analogue
maps the same abstract strings to **jax devices**: the node address picks
the process (node order = launcher ``AUTODIST_PROCESS_ID`` order) and the
ordinal picks that process's local device. The resolved replica list is
what the mesh is built over — so a strategy's replica *order and subset*
have a real runtime effect on device placement.
"""
import jax

from autodist_tpu.utils import logging


class ResolvedDevice:
    """One resolved device: canonical string + the concrete jax device."""

    def __init__(self, canonical, jax_device):
        self.canonical = canonical
        self.jax_device = jax_device

    def __str__(self):
        return self.canonical

    def __repr__(self):
        return '<ResolvedDevice %s>' % self.canonical


class DeviceResolver:
    """Callable resolver bound to a resource spec + visible device set.

    ``resolver('10.0.0.2:TPU:1')`` returns the reference-format canonical
    string ``/job:worker/task:1/device:TPU:1``; :meth:`jax_device_for`
    returns the matching :class:`jax.Device` (or None when the abstract
    string points at a process/ordinal this run does not have).
    """

    _LOCAL_ALIASES = ('localhost', '127.0.0.1', '0.0.0.0')

    def __init__(self, resource_spec, devices=None):
        # chief-first task numbering: launchers assign AUTODIST_PROCESS_ID
        # chief=0 then workers in spec order (launch.py, coordinator.py),
        # and jax process_index follows that — Cluster.cluster_spec parity
        nodes = list(resource_spec.nodes)
        chief = resource_spec.chief
        ordered = [chief] + [n for n in nodes if n != chief]
        self._task_of = {addr: i for i, addr in enumerate(ordered)}
        # single-node specs: any local alias resolves to task 0
        if len(nodes) == 1:
            for alias in self._LOCAL_ALIASES:
                self._task_of.setdefault(alias, 0)
        devices = list(devices if devices is not None else jax.devices())
        # per-process local ordinal -> device (stable id order)
        self._by_proc = {}
        for d in sorted(devices, key=lambda d: d.id):
            self._by_proc.setdefault(d.process_index, []).append(d)

    def __call__(self, abstract):
        """Resolve to the canonical string (StrategyCompiler hook)."""
        r = self.resolve(abstract)
        return r.canonical if r is not None else abstract

    def resolve(self, abstract):
        """'host:KIND:i' (or an already-canonical string) -> ResolvedDevice,
        or None if unresolvable."""
        s = str(abstract)
        if s.startswith('/job:'):
            # already canonical: /job:worker/task:N/device:KIND:I
            try:
                task = int(s.split('/task:')[1].split('/')[0])
                kind, idx = s.split('/device:')[1].split(':')
                idx = int(idx)
            except (IndexError, ValueError):
                return None
        else:
            parts = s.split(':')
            if len(parts) != 3:
                return None
            try:
                host, kind, idx = parts[0], parts[1], int(parts[2])
            except ValueError:
                return None
            task = self._task_of.get(host)
            if task is None:
                return None
        canonical = '/job:worker/task:%d/device:%s:%d' % (task, kind, idx)
        local = self._by_proc.get(task, [])
        dev = local[idx] if idx < len(local) else None
        return ResolvedDevice(canonical, dev)

    def jax_device_for(self, abstract):
        r = self.resolve(abstract)
        return r.jax_device if r is not None else None

    def jax_devices_for(self, abstracts):
        """Ordered jax devices for a replica list; None if any miss
        (callers then fall back to the default device ordering)."""
        out = []
        for a in abstracts:
            d = self.jax_device_for(a)
            if d is None:
                logging.debug('Device %r not resolvable; falling back to '
                              'default mesh device order', a)
                return None
            out.append(d)
        return out if out else None
