"""Cluster management: process identity + multi-host runtime bring-up.

Reference parity: ``autodist/cluster.py`` starts one ``tf.Server`` per node
over SSH and tracks chief/worker identity (:98-147). On TPU there is no
per-op RPC server — the runtime is SPMD program dispatch — so the cluster
layer's jobs reduce to:

1. identity: which process am I, who is chief (reference cluster.py:98-147);
2. bringing up ``jax.distributed`` across hosts (replacing grpc servers);
3. launching worker processes (see :mod:`autodist_tpu.runtime.coordinator`,
   the "re-run the user script on every host" trick, coordinator.py:46-90).
"""
import os
import socket

import jax

from autodist_tpu.const import DEFAULT_JAX_COORD_PORT, ENV
from autodist_tpu.utils import logging


def is_local_address(address):
    """Loopback/local-host detection (reference utils/network.py:22-57)."""
    if address in ('localhost', '0.0.0.0'):
        return True
    try:
        # any loopback /8 IP — but ONLY a literal IP ('127.foo.com' is
        # a legal remote hostname, not loopback)
        import ipaddress
        if ipaddress.ip_address(address).is_loopback:
            return True
    except ValueError:
        pass
    try:
        local = {socket.gethostname(), socket.getfqdn()}
        local_ips = set()
        try:
            local_ips.add(socket.gethostbyname(socket.gethostname()))
        except OSError:
            pass
        try:
            # primary-NIC IP (Debian-style hosts resolve the hostname to
            # 127.0.1.1, missing the real interface address); a UDP
            # connect() learns the outbound IP without sending packets
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.connect(('192.0.2.1', 9))   # TEST-NET, never routed to
            local_ips.add(s.getsockname()[0])
            s.close()
        except OSError:
            pass
        return address in local or address in local_ips
    except OSError:
        return False


class Cluster:
    """Identity + distributed-runtime bring-up for one process."""

    def __init__(self, resource_spec):
        self._resource_spec = resource_spec
        self._started = False
        worker_addr = ENV.AUTODIST_WORKER.val
        self._local_address = worker_addr or resource_spec.chief

    @property
    def is_chief(self):
        return not ENV.AUTODIST_WORKER.val

    def get_local_address(self):
        """This process's node address (reference cluster.py:98-147)."""
        return self._local_address

    @property
    def cluster_spec(self):
        """{'worker': [addr, ...]} with chief first (cluster.py:70-82)."""
        nodes = list(self._resource_spec.nodes)
        chief = self._resource_spec.chief
        ordered = [chief] + [n for n in nodes if n != chief]
        return {'worker': ordered}

    @property
    def num_nodes(self):
        return len(list(self._resource_spec.nodes))

    def start(self):
        """Initialize the distributed runtime if this is a multi-process run.

        Single-host (the common TPU-slice-per-host and all test cases):
        nothing to start — XLA owns the devices already.
        """
        if self._started:
            return
        num_procs = ENV.AUTODIST_NUM_PROCESSES.val
        if num_procs > 1:
            coord = (ENV.AUTODIST_COORDINATOR_ADDR.val or
                     self._resource_spec.coordinator_address or
                     '%s:%d' % (self._resource_spec.chief,
                                DEFAULT_JAX_COORD_PORT))
            pid = ENV.AUTODIST_PROCESS_ID.val
            try:
                # CPU backends need an explicit cross-process collectives
                # implementation (TPU ICI needs none). Must be set before
                # the backend initializes; harmless otherwise.
                jax.config.update('jax_cpu_collectives_implementation',
                                  'gloo')
            except Exception:   # noqa: BLE001 - older jaxlib w/o gloo
                logging.warning('CPU collectives backend unavailable; '
                                'multi-process CPU runs will not form a '
                                'global mesh')
            logging.info('jax.distributed.initialize(%s, %d, %d)',
                         coord, num_procs, pid)
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=num_procs,
                process_id=pid)
        self._started = True

    def terminate(self):
        if self._started and ENV.AUTODIST_NUM_PROCESSES.val > 1:
            try:
                jax.distributed.shutdown()
            except Exception as e:   # noqa: BLE001 - best-effort teardown
                # best-effort, but never silent: a shutdown failure here
                # is the first clue when a later run's initialize hangs
                # on a half-dead coordinator
                logging.warning('jax.distributed.shutdown failed during '
                                'terminate (continuing): %s: %s',
                                type(e).__name__, e)
        self._started = False
