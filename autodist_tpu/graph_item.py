"""GraphItem: the framework's intermediate representation.

Reference parity: ``autodist/graph_item.py:218-553`` wraps a ``tf.Graph``
plus (a) grad→target pairs captured by optimizer monkey-patches, (b) an
``Info`` record replacing TF collections (variables / savers), and (c)
proto serialization.

The TPU-native GraphItem wraps the symbolic :class:`~autodist_tpu.frontend.
graph.Graph` captured under ``ad.scope()`` *or* a user-supplied functional
train step (the primary jax-idiomatic path), and exposes the same queries
the strategy layer needs: trainable variables with shapes/dtypes/sizes,
grad→target pairs, sparsity flags, captured optimizers, and savers.
"""
import json

import numpy as np

from autodist_tpu.frontend import graph as fe


class Info:
    """Collections replacement: variables + savers (graph_item.py:112-215)."""

    def __init__(self):
        self.variables = []    # list of fe.Variable
        self.savers = []

    def update_variables(self, variables, replace=True):
        if replace:
            self.variables = list(variables)
        else:
            self.variables.extend(variables)

    def update_savers(self, savers, replace=True):
        if replace:
            self.savers = list(savers)
        else:
            self.savers.extend(savers)

    @property
    def trainable_variables(self):
        return [v for v in self.variables if v.trainable]


class GraphItem:
    """The captured program handed from the frontend to strategy + backend."""

    def __init__(self, graph=None, step_fn=None, params=None):
        """Either wrap a symbolic ``graph`` or a functional ``step_fn``.

        Args:
            graph: frontend Graph captured under ``ad.scope()``.
            step_fn: pure function ``(state, *batch) -> (metrics, state)``
                for the functional API (``ad.function``).
            params: example state pytree for the functional API.
        """
        self.graph = graph if graph is not None else fe.Graph()
        self.step_fn = step_fn
        self.params = params
        self.info = Info()

    # -- capture-side queries ---------------------------------------------
    @property
    def all_variables(self):
        return list(self.graph.variables.values())

    @property
    def trainable_var_op_to_var(self):
        """name -> Variable (the reference keys by var op; we key by name)."""
        return {v.name: v for v in self.all_variables if v.trainable}

    @property
    def trainable_variables(self):
        return [v for v in self.all_variables if v.trainable]

    @property
    def grad_target_pairs(self):
        """{grad node: target Variable} captured at apply_gradients time."""
        return dict(self.graph.grad_target_pairs)

    @property
    def grad_target_name_pairs(self):
        return {g.name: v.name for g, v in
                self.graph.grad_target_pairs.items()}

    @property
    def optimizers(self):
        """Captured (class name, args, kwargs) tuples."""
        return list(self.graph.optimizers)

    def var_by_name(self, name):
        return self.graph.variables[name]

    def is_sparse(self, var):
        """Whether the variable's gradient is sparse (embedding read)."""
        if isinstance(var, str):
            var = self.var_by_name(var)
        return bool(var.sparse_read)

    def prepare(self):
        """Sync Info from the captured graph (graph_item.py:494-497)."""
        self.info.update_variables(self.all_variables, replace=True)
        self.info.update_savers(self.graph.savers, replace=True)
        return self

    # -- serialization -----------------------------------------------------
    def to_dict(self):
        """Serializable metadata view (variables + grad pairs + optimizers).

        The reference serializes the whole GraphDef (graph_item.py:499-553);
        here program capture is re-run on every process (same design: each
        worker re-executes the user script and re-captures), so only the
        metadata needs round-tripping.
        """
        return {
            'variables': [{
                'name': v.name,
                'shape': list(v.shape),
                'dtype': str(np.dtype(v.dtype).name),
                'trainable': bool(v.trainable),
                'sparse_read': bool(v.sparse_read),
            } for v in self.all_variables],
            'grad_target_pairs': self.grad_target_name_pairs,
            'optimizers': [
                {'class': c, 'args': list(a), 'kwargs': dict(k)}
                for c, a, k in self.optimizers],
        }

    def serialize(self):
        return json.dumps(self.to_dict(), sort_keys=True)

    @staticmethod
    def metadata_from_serialized(s):
        return json.loads(s)
