"""Prospective model of the **strategy-distribution epoch** handshake
(ROADMAP item 2) — verified BEFORE it is implemented.

Cohort-wide lock-step migration needs a new control-plane handshake:
the chief stages plan N+1, peers acknowledge, and the whole cohort
swaps at an agreed step boundary, because an executed re-plan that
re-keys shards or moves a variable between PS endpoints corrupts
state the moment ANY member runs a step under the old plan while
another runs the same step under the new one. The extension contract
in ``docs/design/static-analysis.md`` requires modeling that ordering
here first — this module is that model, and the verified ordering it
proves clean is the implementation contract the ROADMAP 2 PR builds
against (the "Epoch-swap contract" section of the same doc).

**The verified ordering** (:data:`VERIFIED`, must explore clean):

1. chief STAGES plan N+1 (generation-keyed, visible to peers);
2. peers FETCH + ACK (an ack certifies the peer holds the plan and
   can apply it; a peer that cannot, NACKs);
3. the chief ARMS the swap only once every LIVE peer acked and no
   nack exists (deaths degrade via the existing exclude path: the ack
   quorum is re-evaluated over live membership, exactly like the
   staleness gate's party count), publishing the boundary step
   ``B = prefix_min(published) + staleness + 2`` — beyond the
   furthest step any member can be executing before its next
   boundary check (a member executing step ``s`` implies every
   member published ``>= s - staleness - 1``, the gate invariant the
   control-plane model already proves);
4. every member checks the armed boundary at each step start (a
   counter read that piggybacks on the existing gate RPCs) and
   applies plan N+1 before executing step ``B``.

**The seeded tempting-but-wrong orderings** (each must
counterexample — the same sensitivity guard as the historical bugs):

- :data:`SWAP_BEFORE_ACK_QUORUM` — the chief arms right after
  staging, without the ack quorum. A peer that nacked (cannot apply
  the plan) is swapped past: it keeps executing under plan N while
  the rest of the cohort crosses the boundary onto N+1 — the
  mixed-plan write the handshake exists to prevent. (The ack is not
  a formality: without it the chief's only alternatives at the
  boundary are corrupting writes or killing a healthy worker.)
- :data:`NAIVE_BOUNDARY` — ``B = chief's own next step``. Under a
  staleness window a peer may run up to two steps AHEAD of the
  chief, so it has already executed step ``B`` under plan N before
  the commit marker even existed.

What it deliberately does NOT model: the staged plan's payload and
its storage key layout (the contract section in the design doc fixes
generation-keyed staging inside the run namespace and WHY — the
purge/reuse reasoning follows PR 4's durable-marker lesson and needs
no interleaving exploration), fence mechanics of the excluded peer's
zombie writes (``protocol_model``'s zombie scenario owns that), and
the reshard data movement itself (``schedule_lint``'s shape algebra
owns element preservation).
"""
from dataclasses import dataclass, replace

from autodist_tpu.analysis.protocol_model import Scenario, _set_violation


@dataclass(frozen=True)
class EpochSwapConfig:
    """Orderings under test. Defaults are the VERIFIED contract."""

    #: when the chief may arm the swap: 'ack_quorum' (verified — every
    #: live peer acked, no nack) vs 'immediate' (right after staging).
    arm: str = 'ack_quorum'
    #: how the boundary step is chosen: 'prefix_min' (verified —
    #: prefix_min(published) + staleness + 2) vs 'chief_next' (the
    #: chief's own next step — assumes everyone is at its step).
    boundary: str = 'prefix_min'
    #: training steps per member (small scope).
    steps: int = 3
    #: staleness window of the cohort gate.
    staleness: int = 1


VERIFIED = EpochSwapConfig()
#: Seeded wrong ordering 1: arm without the ack quorum.
SWAP_BEFORE_ACK_QUORUM = replace(VERIFIED, arm='immediate')
#: Seeded wrong ordering 2: boundary = the chief's own next step.
NAIVE_BOUNDARY = replace(VERIFIED, boundary='chief_next')


def _members(m, live_only=True):
    out = []
    for n in sorted(m['procs']):
        p = m['procs'][n]
        if p['role'] not in ('swapchief', 'swappeer'):
            continue
        if live_only and m['counters'].get('excluded/' + n, 0) > 0:
            continue
        out.append(n)
    return out


def _gate_ready(m, cfg, s):
    """The cohort staleness gate over live members' published steps."""
    target = s - cfg.staleness
    if target <= 0:
        return True
    vals = [m['counters'].get('step/' + w, 0) for w in _members(m)]
    return min(vals) >= target


def _train_transitions(m, cfg, n, p):
    """One member's training loop: boundary check -> push -> publish
    -> gate, each its own transition. The boundary check at step start
    is where the swap lands; a push records (step, plan generation)
    and cross-checks every earlier push of the same step."""
    s = p['step']
    if s > cfg.steps:
        def fin(m2, n=n):
            m2['procs'][n]['status'] = 'done'
        return [(n, 'finish (clean close)', fin)]

    if p['tphase'] == 'check':
        def check(m2, n=n):
            p2 = m2['procs'][n]
            b = m2['counters'].get('swap/B', 0)
            if b and p2['step'] >= b and p2['gen'] == 0:
                if p2['can_apply']:
                    p2['gen'] = 1
                # an incompatible (nacked) member swapped PAST has no
                # good move; the naive implementation keeps executing
                # plan N — the push below records the damage
            p2['tphase'] = 'push'
        return [(n, 'step %d start: check the swap boundary' % s,
                 check)]

    if p['tphase'] == 'push':
        def push(m2, n=n):
            p2 = m2['procs'][n]
            key = 'stepgen/%d' % p2['step']
            gen = 'N+1' if p2['gen'] else 'N'
            prev = m2['kv'].get(key)
            if prev is not None and prev.split(':')[1] != gen:
                _set_violation(
                    m2, 'mixed-plan-step',
                    'step %d was executed under BOTH plan %s (by %s) '
                    'and plan %s (by %s): with re-keyed shards those '
                    'pushes land on different keys and every variable '
                    'the plans disagree on is corrupted'
                    % (p2['step'], prev.split(':')[1],
                       prev.split(':')[0], gen, n))
            else:
                m2['kv'][key] = '%s:%s' % (n, gen)
            p2['tphase'] = 'publish'
        return [(n, 'pushes step-%d deltas under plan %s'
                 % (s, 'N+1' if p['gen'] else 'N'), push)]

    if p['tphase'] == 'publish':
        def publish(m2, n=n):
            m2['counters']['step/' + n] = s
            m2['procs'][n]['tphase'] = 'gate'
        return [(n, 'publishes step %d' % s, publish)]

    # gate
    if _gate_ready(m, cfg, s):
        def gate(m2, n=n):
            p2 = m2['procs'][n]
            p2['step'] += 1
            p2['tphase'] = 'check'
        return [(n, 'gate passes (step %d)' % s, gate)]
    return []


def _chief_transitions(m, cfg, n, p):
    """The chief trains like any member; its swap-coordination
    transitions (stage, arm, exclude-a-dead-peer) are enabled
    alongside — the explorer's branching models the real daemon
    thread."""
    ts = _train_transitions(m, cfg, n, p)
    if not m['kv'].get('swap/stage'):
        def stage(m2, n=n):
            m2['kv']['swap/stage'] = '1'
        ts.append((n, 'chief stages plan N+1', stage))
    elif not m['counters'].get('swap/B', 0):
        peers = [w for w in _members(m) if w != n]
        acks = m['counters'].get('swap/acks', 0)
        nacks = m['counters'].get('swap/nacks', 0)
        may_arm = (cfg.arm == 'immediate' or
                   (acks >= len(peers) and nacks == 0))
        if may_arm:
            def arm(m2, n=n):
                if cfg.boundary == 'chief_next':
                    b = m2['procs'][n]['step'] + 1
                else:
                    vals = [m2['counters'].get('step/' + w, 0)
                            for w in _members(m2)]
                    b = min(vals) + cfg.staleness + 2
                m2['counters']['swap/B'] = b
            ts.append((n, 'chief arms the swap (publishes boundary '
                       'step)', arm))
    # deaths degrade via the exclude path (ground-truth detection, as
    # in the control-plane model; the path's own ordering is proved
    # there)
    for w in _members(m):
        if w != n and m['procs'][w]['status'] == 'crashed':
            def exclude(m2, n=n, w=w):
                m2['counters']['excluded/' + w] = 1
            ts.append((n, 'excludes dead peer %s (heartbeat timeout)'
                       % w, exclude))
    return ts


def _peer_transitions(m, cfg, n, p):
    ts = _train_transitions(m, cfg, n, p)
    if m['kv'].get('swap/stage') and not p['acked']:
        if p['can_apply']:
            def ack(m2, n=n):
                m2['counters']['swap/acks'] = \
                    m2['counters'].get('swap/acks', 0) + 1
                m2['procs'][n]['acked'] = True
            ts.append((n, 'fetches plan N+1 and ACKs', ack))
        else:
            def nack(m2, n=n):
                m2['counters']['swap/nacks'] = \
                    m2['counters'].get('swap/nacks', 0) + 1
                m2['procs'][n]['acked'] = True
            ts.append((n, 'NACKs plan N+1 (cannot apply it)', nack))
    return ts


def proc_transitions(m, cfg, n):
    p = m['procs'][n]
    if p['status'] != 'running':
        return []
    if p['role'] == 'swapchief':
        return _chief_transitions(m, cfg, n, p)
    return _peer_transitions(m, cfg, n, p)


def describe_stuck(m):
    lines = []
    for n in sorted(m['procs']):
        p = m['procs'][n]
        if p['status'] not in ('running', 'stalled'):
            continue
        lines.append('%s is blocked at the step-%d gate (plan %s)'
                     % (n, p.get('step', 0),
                        'N+1' if p.get('gen') else 'N'))
    return '; '.join(lines) or 'no live process has an enabled ' \
                               'transition'


def _terminal_check(m):
    """At rest, every live member must have finished under the SAME
    plan generation — a cohort split across generations is exactly
    the divergence the boundary agreement exists to prevent."""
    gens = {}
    for n in _members(m):
        p = m['procs'][n]
        if p['status'] == 'done':
            gens[n] = 'N+1' if p['gen'] else 'N'
    if len(set(gens.values())) > 1:
        return [('swap-divergence',
                 'the cohort finished split across plan generations: '
                 '%s — members on plan N keep using the old shard '
                 'keys forever' % (', '.join(
                     '%s on %s' % kv for kv in sorted(gens.items()))))]
    return []


def _member(n, role, can_apply=True):
    return {'role': role, 'status': 'running', 'step': 1,
            'tphase': 'check', 'gen': 0, 'can_apply': can_apply,
            'acked': False, 'stall_budget': 0}


def _scenario(name, cfg, procs, **kw):
    model = {'counters': {}, 'kv': {}, 'procs': procs,
             'slot_owner': {}, 'crash_budget': kw.pop('crash_budget', 0),
             'violation': None}
    kw.setdefault('transitions_fn', proc_transitions)
    kw.setdefault('describe_stuck', describe_stuck)
    kw.setdefault('terminal_check', _terminal_check)
    return Scenario(name, cfg, model, **kw)


def swap_scenario(cfg):
    """Chief + a compatible peer that may crash anywhere (deaths
    degrade via the exclude path: the ack quorum and the gate both
    re-evaluate over live membership). The NAIVE_BOUNDARY ordering
    must counterexample here; the verified ordering explores clean.
    Two members keep the space small — the boundary race needs only
    one peer running ahead of the chief, and a second peer multiplies
    states without adding a new interleaving class (the ack quorum is
    a count either way)."""
    procs = {'c': _member('c', 'swapchief'),
             'p1': _member('p1', 'swappeer')}
    return _scenario('epoch_swap', cfg, procs, crash_budget=1,
                     crashable=('p1',))


def swap_nack_scenario(cfg):
    """Chief + a peer that NACKs (cannot apply plan N+1). Verified:
    the chief never arms, everyone finishes on plan N.
    SWAP_BEFORE_ACK_QUORUM must counterexample here (the chief
    crosses the boundary onto N+1 while the swapped-past peer keeps
    pushing N)."""
    procs = {'c': _member('c', 'swapchief'),
             'p2': _member('p2', 'swappeer', can_apply=False)}
    return _scenario('epoch_swap_nack', cfg, procs)


def scenarios(cfg):
    """The epoch-swap scenario suite for one configuration."""
    return [swap_scenario(cfg), swap_nack_scenario(cfg)]


#: The sensitivity guard: each tempting-but-wrong ordering must yield
#: its counterexample in the named scenario.
SEEDED_BUGS = (
    ('swap armed before the ack quorum (nacked peer swapped past)',
     SWAP_BEFORE_ACK_QUORUM, 'epoch_swap_nack', 'mixed-plan-step'),
    ('boundary = chief\'s own next step (peer already past it)',
     NAIVE_BOUNDARY, 'epoch_swap', 'mixed-plan-step'),
)

#: Exploration statistics of the last :func:`analyze` run.
LAST_STATS = {}


def analyze():
    """The epoch-swap analyzer: the VERIFIED handshake ordering must
    explore clean AND both tempting-but-wrong orderings must still
    counterexample. Returns finding strings (empty = clean)."""
    from autodist_tpu.analysis import explore
    LAST_STATS.clear()
    return explore.run_suite(VERIFIED, scenarios, SEEDED_BUGS,
                             'epoch-swap model', stats=LAST_STATS)
