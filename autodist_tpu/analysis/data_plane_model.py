"""Executable small-scope model of the PS **data plane**.

The control-plane checker (:mod:`~autodist_tpu.analysis.
protocol_model`) covers membership, fencing and gate orderings — but
three of the four review passes' worth of real concurrency bugs lived
one layer down, in the tensor data plane, guarded until now only by
hand reasoning:

- **PR 1's offset-0 abort**: ``abort_open_seq`` decremented
  ``open_writes`` for ANY rejected frame, so one malformed offset-0
  frame (which never opened a sequence — ``SeqFrame`` is constructed
  after the checks) closed ANOTHER writer's in-flight chunked
  sequence and cleared the torn-read parity bit under its feet — a
  reader then accepted half-written data as clean.
- **PR 5's disconnect wedge**: a writer killed between chunks (the
  exclude/restart policies' core died-mid-push case) sent no further
  frames; without the disconnect-time ``SeqAborter`` its sequence
  held ``open_writes`` forever and every reader retried odd parity
  until a ``DELNS``.
- **PR 11's telemetry-cursor race**: ``push_records`` bumps the
  atomic batch counter BEFORE the tensor write lands, so a monitor
  poll racing an in-flight push saw the seq but not the bytes — a
  cursor that advanced to the counter dropped that batch forever.

This module models exactly the cross-process data-plane state those
bugs live in, reusing the explorer unchanged via the
:class:`~autodist_tpu.analysis.protocol_model.Scenario` hooks:

- the **tensor store**: per-key ``version``/``open_writes`` torn-read
  bookkeeping, ``SeqFrame`` chunked-sequence semantics (offset-0
  opens, final chunk closes, every rejection aborts), the offset-0
  abort rule, the disconnect-time ``SeqAborter``, and the B*
  fence-recheck-under-tensor-lock window (the wire-entry check and
  the commit are separate transitions, so a fence bump can land
  between them) — shared by the dense (BSET/BADD) and row-sparse
  (BSADD, ranges counting ROWS) writers, which differ only in what a
  "chunk" is;
- the **versioned reader** (BGET/BGETROWS ``v`` contract): a
  multi-chunk read accepts only when both version snapshots are even
  and equal, else retries — exactly ``coord_client``'s torn-read
  loop;
- the **session pipeline at depth 2**: join → gate → serve-prefetch →
  push → publish → peer-floor scan → pull-ahead, every RPC its own
  transition, with the peer-floor staleness guard (``run()`` discards
  a prefetch whose recorded floor is below the next step's staleness
  bound) and its ordering (floor read after publish, before the
  pull-ahead) as configuration;
- the **telemetry batch-counter/cursor protocol**: counter bump and
  batch write as separate transitions (the real race window), the
  monitor's incremental cursor with its advance rule as
  configuration, and a close-time final sweep;
- the **local-SGD window** (sync rounds at ``local_steps`` H > 1):
  round-scoped gate → pull merged state → H local steps (no wire
  traffic) → one window-delta push whose merge rule ('average' =
  workers push delta/W so the PS lands the MEAN of the windows, vs
  the naive 'sum') and the gate's counter scope (sync ROUNDS vs raw
  train steps) are the configuration under test;
- the **serving snapshot seqlock** (ISSUE 17's reader fleet): the
  trainer's per-round parity-odd → push tensors → publish → parity-
  even window (``session._snap_round_open/_close``) against
  non-voting replicas pulling multi-tensor snapshots, with the
  replica's ordering (pin parities+step first, pull, revalidate — vs
  the tempting read-then-stamp) as configuration and the writer
  crashable mid-round (the parity-stuck-odd keep-old-snapshot trade).

Invariants:

- **no torn read surfaces as clean** — an accepted (even, equal
  version) read must never observe chunks of a still-open write
  sequence (a sequence *aborted* by disconnect or rejection is
  legitimate partial data the staleness model absorbs — that is the
  service's documented contract, and the model encodes it);
- **no fenced zombie frame commits** after its fence bump (the
  under-tensor-lock re-check);
- **no reader wedges on odd parity after ANY writer death**
  (liveness: the stuck diagnosis names the wedged reader and the key
  whose parity is stuck odd);
- **prefetches never violate the serial staleness bound** — a served
  prefetch must contain every peer push the gate just guaranteed;
- **the cursor never permanently skips a decodable batch** (terminal
  invariant: after the final sweep, every batch whose bytes landed
  was consumed);
- **the H-step staleness bound** — a worker pulling at sync round r
  observes every peer's window pushes through round r − staleness,
  so no reader ever sees state older than H × gate_staleness train
  steps — and **window merges never diverge**: the PS total equals
  the mean of the pushed windows (the sum-not-average push is the
  pinned W-fold-overshoot counterexample);
- **no snapshot mixes tensor versions from different published
  steps** — an ACCEPTED serving snapshot's tensors all carry the one
  step it is stamped with (a replica losing its writer mid-round
  gives up and keeps serving its previous snapshot; it never blocks
  the trainer and never accepts the torn round).

What it deliberately does NOT model: payload values and shapes (the
chunk stamps track write identity, not bytes — BSADD's index/shape
validation is the fence lint's and the real service tests' job),
multi-key stores (one tensor key per scenario; per-key locks don't
interact), the stall-window timeout of the reader's retry loop
(unbounded retry + liveness detection is strictly stronger), wire
dtypes, and the control-plane orderings already covered by
``protocol_model``. See ``docs/design/static-analysis.md``.
"""
from dataclasses import dataclass, replace

from autodist_tpu.analysis.protocol_model import Scenario, _set_violation


@dataclass(frozen=True)
class DataPlaneConfig:
    """Orderings under test. Defaults are HEAD's (must explore clean);
    each historical bug is one field flipped back."""

    #: which rejected frames abort an open sequence: 'continuation_only'
    #: (HEAD — only a declared offset > 0 frame can have opened one)
    #: vs 'any_frame' (the pre-PR 1 rule: a malformed offset-0 frame
    #: closes ANOTHER writer's sequence).
    abort_offset0: str = 'continuation_only'
    #: whether a dead connection's open chunk sequences are aborted at
    #: disconnect (HEAD's SeqAborter) — False is the pre-PR 5 service.
    disconnect_abort: bool = True
    #: where B* handlers check the fence: 'under_lock' (HEAD — the
    #: commit re-checks under the tensor lock) vs 'entry_only' (the
    #: wire-entry check alone; one in-flight zombie frame can commit
    #: after its fence bump).
    fence_recheck: str = 'under_lock'
    #: run()'s prefetch guard: 'floor_discard' (HEAD — a prefetch whose
    #: recorded peer floor is below the next step's staleness bound is
    #: discarded) vs 'serve_always' (the pre-review PR 3 pipeline).
    prefetch_guard: str = 'floor_discard'
    #: when the pipeline job reads the peer floor: 'after_publish'
    #: (HEAD — push -> publish -> floor -> pull-ahead, so the floor
    #: lower-bounds what the pull observed) vs 'after_pull' (floor
    #: read last, overstating what the prefetch contains).
    floor_scan: str = 'after_publish'
    #: the monitor cursor's advance rule: 'decoded_prefix' (HEAD — the
    #: consumed prefix stops at the first not-yet-landed batch) vs
    #: 'counter' (pre-PR 11: advance to the counter, dropping the
    #: in-flight batch forever).
    cursor_advance: str = 'decoded_prefix'
    #: training steps per worker in the pipeline scenario.
    steps: int = 2
    #: staleness window of the pipeline scenario's gate.
    staleness: int = 1
    #: mid-run monitor polls in the telemetry scenario (the close-time
    #: final sweep is extra).
    polls: int = 2
    #: local-SGD window length H in the local_sgd scenario: each
    #: worker takes H local optimizer steps per sync round, then
    #: pushes ONE window delta. Kept integer-divisible by the worker
    #: count so the merged mean is exact integer arithmetic.
    local_steps: int = 2
    #: the window merge rule: 'average' (HEAD — the session scales the
    #: pushed delta by 1/W so the commutative BADD lands the MEAN of
    #: the workers' windows) vs 'sum' (the naive push: the PS total
    #: overshoots W-fold, the pinned divergence counterexample).
    window_merge: str = 'average'
    #: the staleness gate's counter scope under H > 1: 'rounds' (HEAD
    #: — gate_at and the published floors both count sync ROUNDS) vs
    #: 'steps' (the gate target scaled to raw train steps while peers
    #: still publish rounds — the mixed-scope deadlock the coordinator
    #: forwards AUTODIST_LOCAL_STEPS to prevent).
    gate_scope: str = 'rounds'
    #: the serving replica's snapshot ordering (ISSUE 17):
    #: 'pin_then_read' (HEAD — pin the seqlock parities + published
    #: step FIRST, pull every tensor, revalidate the parities, accept
    #: iff unchanged) vs 'read_then_pin' (the tempting-but-wrong
    #: ordering: pull the tensors, THEN read the parity/step and stamp
    #: the snapshot — a writer completing a whole sync round between
    #: two tensor reads yields an undetectably mixed snapshot).
    snapshot_order: str = 'pin_then_read'
    #: the trainer's snap-parity behavior across an epoch-swap re-key
    #: (PR 19): 'bump' (HEAD — ``session._execute_replan`` brackets
    #: the re-key in ``_snap_round_open/_close``, so a replica pull
    #: straddling the swap boundary can never revalidate its pinned
    #: parity) vs 'silent' (re-key the tensors without touching the
    #: parity — a replica that pinned before the swap and read across
    #: it revalidates an UNCHANGED parity and accepts a snapshot
    #: mixing pre- and post-swap shard layouts: with overlapping key
    #: names of different geometry, merged garbage).
    swap_parity: str = 'bump'


HEAD = DataPlaneConfig()
#: PR 1's historical bug: any rejected frame decremented open_writes.
PR1_OFFSET0_ABORT = replace(HEAD, abort_offset0='any_frame')
#: PR 5's historical bug: no disconnect-time sequence abort.
PR5_DISCONNECT_WEDGE = replace(HEAD, disconnect_abort=False)
#: PR 11's historical bug: the cursor advanced to the batch counter.
PR11_CURSOR_RACE = replace(HEAD, cursor_advance='counter')
#: Same class, not historical: fence checked at wire entry only.
UNLOCKED_FENCE_RECHECK = replace(HEAD, fence_recheck='entry_only')
#: ...the pipeline serving a too-early prefetch unguarded...
NO_FLOOR_DISCARD = replace(HEAD, prefetch_guard='serve_always')
#: ...and the floor read AFTER the pull-ahead it must lower-bound.
FLOOR_AFTER_PULL = replace(HEAD, floor_scan='after_pull')
#: The local-SGD window pushed raw (sum of local deltas, no 1/W
#: scale): every sync round the PS overshoots W-fold.
LOCAL_SGD_SUM = replace(HEAD, window_merge='sum')
#: The gate target scaled to train steps while peers publish sync
#: rounds: every worker blocks at its first gate forever.
LOCAL_SGD_STEP_GATE = replace(HEAD, gate_scope='steps')
#: The serving replica pulling its tensors BEFORE pinning the
#: parity/step: a writer completing a whole round between two tensor
#: reads serves an undetectably mixed snapshot.
SNAPSHOT_READ_BEFORE_PIN = replace(HEAD, snapshot_order='read_then_pin')
#: The epoch-swap re-key applied WITHOUT the snap-parity bracket: a
#: replica pull straddling the swap boundary revalidates clean and
#: serves a snapshot mixing the two shard layouts.
SWAP_SILENT_REKEY = replace(HEAD, swap_parity='silent')


# -- tensor-store semantics ----------------------------------------------

def _t_open(m, key):
    return m['counters'].get('t/%s/open' % key, 0)


def _t_ver(m, key):
    return m['counters'].get('t/%s/ver' % key, 0)


def _t_parity(m, key):
    """The BGET/BGETROWS 'v' reply: version*2 + (open_writes>0)."""
    return _t_ver(m, key) * 2 + (1 if _t_open(m, key) > 0 else 0)


def _seq_open(m, key, proc):
    """The write-id ``proc``'s connection holds open on ``key``
    (conn->open_seqs), or ''."""
    return m['kv'].get('seq/%s/%s' % (key, proc), '')


def seq_open_frame(m, key, proc, wid):
    """Offset-0 frame of a chunked write: opens the sequence
    (``++open_writes``, conn->open_seqs.insert)."""
    m['counters']['t/%s/open' % key] = _t_open(m, key) + 1
    m['kv']['seq/%s/%s' % (key, proc)] = wid


def seq_close(m, key, proc):
    """Release one open_writes slot + the connection's open-seq entry
    — the shared tail of finish(final), fail() and the aborts."""
    if _t_open(m, key) > 0:
        m['counters']['t/%s/open' % key] = _t_open(m, key) - 1
    m['kv'].pop('seq/%s/%s' % (key, proc), None)


def seq_abort_rejected(m, cfg, key, proc, off_declared):
    """abort_open_seq: a rejected frame's cleanup. HEAD only aborts
    when the frame DECLARED a continuation offset (off > 0) — an
    offset-0 frame never opened a sequence, so decrementing for it
    closes another writer's. The pre-PR 1 rule decrements for any
    rejected frame."""
    if cfg.abort_offset0 == 'continuation_only' and off_declared <= 0:
        return
    # the pre-fix decrement hits the TENSOR counter even though this
    # connection opened nothing — exactly the bug
    if _t_open(m, key) > 0:
        m['counters']['t/%s/open' % key] = _t_open(m, key) - 1
    m['kv'].pop('seq/%s/%s' % (key, proc), None)


def disconnect_abort(m, cfg, proc):
    """serve_conn's SeqAborter: abort every sequence the dead
    connection still holds open (HEAD); the pre-PR 5 service leaked
    them."""
    if not cfg.disconnect_abort:
        return
    for k in [k for k in m['kv'] if k.startswith('seq/')
              and k.endswith('/' + proc)]:
        key = k.split('/')[1]
        seq_close(m, key, proc)


def _fenced(m, proc):
    p = m['procs'][proc]
    fk = p.get('fence_key')
    return bool(fk) and m['counters'].get(fk, 0) > p.get('fence_gen', 0)


# -- process roles --------------------------------------------------------

def _writer_transitions(m, cfg, n, p):
    """A chunked B* writer (BSET/BADD dense chunks or BSADD row
    ranges — identical SeqFrame semantics; ``p['sparse']`` only labels
    the frames). One 2-chunk sequence: the offset-0 frame, then the
    final frame split into wire-entry and under-lock commit so the
    fence-recheck window is explored."""
    key = p['tkey']
    kind = 'BSADD rows' if p['sparse'] else 'BSET chunk'
    if p['wphase'] == 'w0':
        def w0(m2, n=n):
            p2 = m2['procs'][n]
            if _fenced(m2, n):
                # rejected at wire entry; an offset-0 frame opened
                # nothing, so there is nothing to abort (HEAD) — but
                # the pre-PR 1 rule aborts anyway
                seq_abort_rejected(m2, cfg, key, n, 0)
                p2['status'] = 'failed'
                return
            wid = '%s#%d' % (n, p2['wseq'])
            seq_open_frame(m2, key, n, wid)
            m2['kv']['t/%s/c0' % key] = wid
            m2['counters']['t/%s/ver' % key] = _t_ver(m2, key) + 1
            p2['wphase'] = 'w1e'
        return [(n, 'writes %s 0 of write %s#%d (opens sequence, '
                 'parity goes odd)' % (kind, n, p['wseq']), w0)]
    if p['wphase'] == 'w1e':
        def w1_entry(m2, n=n):
            p2 = m2['procs'][n]
            if _fenced(m2, n):
                # rejected at wire entry: a continuation frame aborts
                # the sequence it opened so readers are not wedged
                seq_abort_rejected(m2, cfg, key, n, 1)
                p2['status'] = 'failed'
                return
            p2['wphase'] = 'w1c'
        return [(n, 'final %s of %s#%d passes the wire-entry fence '
                 'check' % (kind, n, p['wseq']), w1_entry)]
    if p['wphase'] == 'w1c':
        def w1_commit(m2, n=n):
            p2 = m2['procs'][n]
            if _fenced(m2, n):
                if cfg.fence_recheck == 'under_lock':
                    # reject_fenced_under_tensor_lock: the re-check
                    # under the tensor lock aborts the sequence
                    seq_close(m2, key, n)
                    p2['status'] = 'failed'
                    return
                # entry_only: the zombie frame commits anyway
                _set_violation(
                    m2, 'zombie-frame-commit',
                    'the final %s of %s committed AFTER its fence '
                    'bump: the wire-entry check alone leaves a window '
                    '— B* handlers must re-check the fence under the '
                    'tensor lock' % (kind, n))
            wid = '%s#%d' % (n, p2['wseq'])
            m2['kv']['t/%s/c1' % key] = wid
            m2['counters']['t/%s/ver' % key] = _t_ver(m2, key) + 1
            seq_close(m2, key, n)
            p2['wseq'] += 1
            if p2['wseq'] > p2['writes']:
                p2['status'] = 'done'
            else:
                p2['wphase'] = 'w0'
        return [(n, 'final %s of %s#%d commits (closes sequence, '
                 'version bumps)' % (kind, n, p['wseq']), w1_commit)]
    raise AssertionError(p['wphase'])


def _malformed_transitions(m, cfg, n, p):
    """A writer whose single offset-0 frame is malformed and rejected
    before any SeqFrame exists (bad payload / bad range) — the PR 1
    trigger."""
    def reject(m2, n=n):
        seq_abort_rejected(m2, cfg, p['tkey'], n, 0)
        m2['procs'][n]['status'] = 'done'
    return [(n, 'malformed offset-0 frame is rejected (ERR bad '
             'payload)', reject)]


def _reader_transitions(m, cfg, n, p):
    """The coord_client torn-read loop over a 2-chunk versioned read:
    accept only when both version snapshots are even and equal, else
    retry. An accepted read that observed a chunk of a still-OPEN
    sequence is the torn-read violation."""
    key = p['tkey']
    if p['rphase'] == 'r0':
        def r0(m2, n=n):
            p2 = m2['procs'][n]
            p2['ver0'] = _t_parity(m2, key)
            p2['saw0'] = m2['kv'].get('t/%s/c0' % key, 'init')
            p2['rphase'] = 'r1'
        return [(n, 'reads chunk 0 + version (BGET v)', r0)]

    def r1(m2, n=n):
        p2 = m2['procs'][n]
        ver1 = _t_parity(m2, key)
        saw1 = m2['kv'].get('t/%s/c1' % key, 'init')
        if p2['ver0'] % 2 or ver1 % 2 or p2['ver0'] != ver1:
            p2['rphase'] = 'r0'   # torn: retry (coord_client backoff)
            return
        # accepted as CLEAN: neither chunk may come from a sequence
        # that is still open (aborted partial data is legitimate
        # bounded-lag state; in-flight data is a torn read)
        open_wids = {m2['kv'][k] for k in m2['kv']
                     if k.startswith('seq/%s/' % key)}
        for saw in (p2['saw0'], saw1):
            if saw in open_wids:
                _set_violation(
                    m2, 'torn-read-clean',
                    'reader %s accepted a CLEAN read (version even '
                    'and stable) that observed chunk data of the '
                    'still-open write sequence %s — the parity bit '
                    'was cleared under the writer\'s feet' % (n, saw))
        p2['status'] = 'done'
    return [(n, 'reads chunk 1 + version; accept iff even and '
             'unchanged', r1)]


def _fencer_transitions(m, cfg, n, p):
    """The exclude path's fence bump, abstracted to one transition
    (its own ordering is protocol_model's domain): enabled only when
    the target is stalled/crashed — the heartbeat-timeout ground-truth
    abstraction."""
    w = p['target']
    st = m['procs'][w]['status']
    ts = []
    if not p['bumped'] and st in ('stalled', 'crashed'):
        def bump(m2, n=n, w=w):
            fk = m2['procs'][w]['fence_key']
            m2['counters'][fk] = m2['counters'].get(fk, 0) + 1
            m2['procs'][n]['bumped'] = True
        ts.append((n, 'declares %s dead and bumps its fence '
                   '(exclude path)' % w, bump))
    if p['bumped'] or st in ('done', 'failed'):
        def fin(m2, n=n):
            m2['procs'][n]['status'] = 'done'
        ts.append((n, 'fencer done', fin))
    return ts


# -- depth-2 pipeline ------------------------------------------------------

def _pipe_transitions(m, cfg, n, p):
    """One loose-mode worker at pipeline depth 2. Each RPC of the
    run() loop and of the background job is its own transition:
    join -> gate -> serve (prefetch or fresh pull) -> push -> publish
    -> peer-floor scan -> pull-ahead -> next step. 'data/<w>' counters
    are push counts (push -> publish order holds by construction, as
    in the session); the prefetch record carries the floor it scanned
    and the per-peer push counts its pull actually observed."""
    s = p['step']
    peers = [w for w in sorted(m['procs'])
             if m['procs'][w]['role'] == 'pworker' and w != n]

    if p['pphase'] == 'gate':
        # join happened implicitly: the prefetch record is already in
        # p (the bg job's transitions completed before run() proceeds
        # — run() joins the pipeline first, so own-thread overlap
        # never touches shared state)
        target = s - cfg.staleness
        steps = [m['counters'].get('step/%s' % w, 0)
                 for w in sorted(m['procs'])
                 if m['procs'][w]['role'] == 'pworker']
        if target <= 0 or min(steps) >= target:
            def gate(m2, n=n):
                m2['procs'][n]['pphase'] = 'serve'
            return [(n, 'gate passes (step %d)' % s, gate)]
        return []   # blocked: MINWAIT (liveness catches deadlock)

    if p['pphase'] == 'serve':
        def serve(m2, n=n):
            p2 = m2['procs'][n]
            bound = p2['step'] - cfg.staleness
            if p2['pf_floor'] >= 0:   # a prefetch is in hand
                use = True
                if cfg.prefetch_guard == 'floor_discard' and \
                        p2['pf_floor'] < bound:
                    use = False   # discard; the refetch is serial
                if use:
                    # the serial-staleness invariant: the served pull
                    # must contain every peer push the gate guarantees
                    observed = dict(p2['pf_seen'])
                    for i, w in enumerate(peers):
                        if observed.get(w, 0) < bound:
                            _set_violation(
                                m2, 'stale-prefetch',
                                'worker %s served a prefetch at step '
                                '%d whose pull observed only %d '
                                'push(es) from %s (< the staleness '
                                'bound %d the gate just guaranteed) '
                                '— recorded floor %d let it through'
                                % (n, p2['step'], observed.get(w, 0),
                                   w, bound, p2['pf_floor']))
                p2['pf_floor'] = -1
                p2['pf_seen'] = ()
            # fresh pull (or post-discard refetch) is an atomic read
            # of current state: trivially within the bound
            p2['pphase'] = 'push'
        return [(n, 'serves the step-%d pull (prefetch or fresh)' % s,
                 serve)]

    if p['pphase'] == 'push':
        def push(m2, n=n):
            m2['counters']['data/%s' % n] = \
                m2['counters'].get('data/%s' % n, 0) + 1
            m2['procs'][n]['pphase'] = 'publish'
        return [(n, 'bg: pushes step-%d delta' % s, push)]

    if p['pphase'] == 'publish':
        def publish(m2, n=n):
            m2['counters']['step/%s' % n] = s
            p2 = m2['procs'][n]
            if p2['step'] >= cfg.steps:
                p2['status'] = 'done'   # last step: no pull-ahead
            elif cfg.floor_scan == 'after_publish':
                p2['pphase'] = 'floor'
            else:
                p2['pphase'] = 'pull'
        return [(n, 'bg: publishes step %d' % s, publish)]

    if p['pphase'] == 'floor':
        def floor(m2, n=n):
            p2 = m2['procs'][n]
            vals = [m2['counters'].get('step/%s' % w, 0)
                    for w in peers] or [s]
            p2['pf_floor'] = min(min(vals), s)
            p2['pphase'] = 'pull' if cfg.floor_scan == \
                'after_publish' else 'next'
        return [(n, 'bg: scans peer step counters for the floor',
                 floor)]

    if p['pphase'] == 'pull':
        def pull(m2, n=n):
            p2 = m2['procs'][n]
            p2['pf_seen'] = tuple(sorted(
                (w, m2['counters'].get('data/%s' % w, 0))
                for w in peers))
            p2['pphase'] = 'next' if cfg.floor_scan == \
                'after_publish' else 'floor'
        return [(n, 'bg: pull-ahead snapshots peer state', pull)]

    # 'next': advance to the next run() iteration
    def nxt(m2, n=n):
        p2 = m2['procs'][n]
        p2['step'] += 1
        p2['pphase'] = 'gate'
    return [(n, 'run() returns; next step begins', nxt)]


# -- local-SGD window ------------------------------------------------------

def _lworker_transitions(m, cfg, n, p):
    """One loose-mode worker under local-SGD ``H = cfg.local_steps``:
    round-scoped gate → pull merged state → H local steps (pure
    device work, no wire traffic) → one window-delta push (the merge
    rule is the configuration) → publish the sync round. Integer
    arithmetic throughout: a local step contributes +1 to the window
    delta, so under 'average' each push lands ``H // W`` on the PS
    counter and the merged total stays exactly the mean of the
    workers' windows."""
    r = p['round']
    workers = [w for w in sorted(m['procs'])
               if m['procs'][w]['role'] == 'lworker']
    peers = [w for w in workers if w != n]

    if p['lphase'] == 'gate':
        # the staleness gate re-scoped to sync rounds: gate_at = r,
        # floors are published ROUND counters. The 'steps' scope is
        # the mixed-scope bug — the target inflates H-fold while the
        # floors stay in rounds, so no gate ever passes again.
        target = r - cfg.staleness
        if cfg.gate_scope == 'steps':
            target = r * cfg.local_steps - cfg.staleness
        floors = [m['counters'].get('round/%s' % w, 0) for w in workers]
        if target <= 0 or min(floors) >= target:
            def gate(m2, n=n):
                m2['procs'][n]['lphase'] = 'pull'
            return [(n, 'round-%d gate passes (floors in sync rounds)'
                     % r, gate)]
        return []   # blocked: MINWAIT (liveness catches deadlock)

    if p['lphase'] == 'pull':
        def pull(m2, n=n):
            p2 = m2['procs'][n]
            # the H-step staleness bound: the gate just guaranteed
            # every peer published round >= r - staleness, and pushes
            # land BEFORE publishes, so the merged state this pull
            # observes contains every peer window through that round
            # — i.e. nothing older than H x gate_staleness steps
            bound = p2['round'] - cfg.staleness
            for w in peers:
                if m2['counters'].get('round/%s' % w, 0) < bound:
                    _set_violation(
                        m2, 'stale-window-read',
                        'worker %s pulled at sync round %d but peer '
                        '%s had only published round %d (< the bound '
                        '%d) — the merged state is older than '
                        'H x gate_staleness train steps'
                        % (n, p2['round'], w,
                           m2['counters'].get('round/%s' % w, 0),
                           bound))
            p2['lstep'] = 0
            p2['lphase'] = 'local'
        return [(n, 'pulls merged state for round %d' % r, pull)]

    if p['lphase'] == 'local':
        def step(m2, n=n):
            p2 = m2['procs'][n]
            p2['lstep'] += 1
            if p2['lstep'] >= cfg.local_steps:
                p2['lphase'] = 'push'
        return [(n, 'local step %d/%d of round %d (no wire traffic)'
                 % (p['lstep'] + 1, cfg.local_steps, r), step)]

    if p['lphase'] == 'push':
        def push(m2, n=n):
            # 'average': the session scales the window delta by 1/W
            # before the commutative BADD; 'sum' is the naive raw push
            amt = cfg.local_steps
            if cfg.window_merge == 'average':
                amt = cfg.local_steps // len(workers)
            m2['counters']['ps/T'] = \
                m2['counters'].get('ps/T', 0) + amt
            m2['counters']['pushed/%s' % n] = \
                m2['counters'].get('pushed/%s' % n, 0) + 1
            m2['procs'][n]['lphase'] = 'publish'
        return [(n, 'pushes the %s window delta of round %d'
                 % (cfg.window_merge, r), push)]

    # 'publish': bump the round floor; the last round ends the worker
    def publish(m2, n=n):
        p2 = m2['procs'][n]
        m2['counters']['round/%s' % n] = r
        if p2['round'] >= cfg.steps:
            p2['status'] = 'done'
        else:
            p2['round'] += 1
            p2['lphase'] = 'gate'
    return [(n, 'publishes sync round %d' % r, publish)]


def _local_sgd_terminal_check(m):
    """The window-merge divergence invariant: once every worker is
    done, the PS total must equal the MEAN of the pushed windows —
    total_pushes x H / W. The sum-not-average push lands W x that."""
    workers = sorted(w for w in m['procs']
                     if m['procs'][w]['role'] == 'lworker')
    if not workers:
        return []
    h = m['procs'][workers[0]]['h']
    pushes = sum(m['counters'].get('pushed/%s' % w, 0)
                 for w in workers)
    expect = pushes * h // len(workers)
    ps = m['counters'].get('ps/T', 0)
    if ps != expect:
        return [(
            'window-sum-divergence',
            'after %d window push(es) of H=%d across %d workers the '
            'PS total is %d, not the window mean %d — the deltas '
            'were pushed raw (sum) instead of scaled by 1/W, so the '
            'merged state overshoots W-fold every sync round'
            % (pushes, h, len(workers), ps, expect))]
    return []


# -- serving snapshot seqlock (ISSUE 17) -----------------------------------

def _snap_parity(m, writers):
    """The replica's parity pin: the sum of every trainer's snap
    counter. Any in-flight round makes it odd; any COMPLETED round
    changes its value — so 'unchanged across the pull' implies no
    write activity at all, which is exactly what revalidation needs."""
    return sum(m['counters'].get('snap/%s' % w, 0) for w in writers)


def _snap_floor(m, writers):
    """The published floor the snapshot is stamped with: min published
    step across the cohort."""
    return min(m['counters'].get('sstep/%s' % w, 0) for w in writers)


def _swriter_transitions(m, cfg, n, p):
    """The trainer's publish path as the serving tier sees it
    (``session._snap_round_open/_close`` around ``_push_ps_deltas`` +
    ``publish_step``): per sync round the parity counter goes ODD, the
    dense tensors land one by one, the step publishes, the parity
    returns EVEN. A crash between any two transitions leaves the
    parity odd forever — the replica's documented trade is to keep
    serving its previous snapshot, never to block or to accept."""
    r = p['round']
    if p['sphase'] == 'open':
        def sopen(m2, n=n):
            m2['counters']['snap/%s' % n] = \
                m2['counters'].get('snap/%s' % n, 0) + 1
            m2['procs'][n]['sphase'] = 'pushA'
        return [(n, 'snap parity goes ODD for round %d' % r, sopen)]
    if p['sphase'] == 'pushA':
        def push_a(m2, n=n):
            m2['kv']['sv/A'] = r
            m2['procs'][n]['sphase'] = 'pushB'
        return [(n, 'pushes tensor A at round %d' % r, push_a)]
    if p['sphase'] == 'pushB':
        def push_b(m2, n=n):
            m2['kv']['sv/B'] = r
            m2['procs'][n]['sphase'] = 'publish'
        return [(n, 'pushes tensor B at round %d' % r, push_b)]
    if p['sphase'] == 'publish':
        def publish(m2, n=n):
            m2['counters']['sstep/%s' % n] = r
            m2['procs'][n]['sphase'] = 'close'
        return [(n, 'publishes step %d' % r, publish)]
    if p['sphase'] == 'close':
        # parity returns even; the last round either ends the trainer
        # or hands off to a pending epoch-swap re-key
        def sclose(m2, n=n):
            p2 = m2['procs'][n]
            m2['counters']['snap/%s' % n] = \
                m2['counters'].get('snap/%s' % n, 0) + 1
            if p2['round'] >= p2['rounds']:
                if p2.get('swap_pending'):
                    p2['sphase'] = 'swapopen' \
                        if cfg.swap_parity == 'bump' else 'rekeyA'
                else:
                    p2['status'] = 'done'
            else:
                p2['round'] += 1
                p2['sphase'] = 'open'
        return [(n, 'snap parity returns EVEN after round %d' % r,
                 sclose)]

    # -- epoch-swap re-key (PR 19): session._execute_replan moving the
    # authoritative PS values old-keys -> new-keys. Values are moved,
    # never recomputed (sv/* unchanged); what changes is the shard
    # LAYOUT (lay/*). HEAD brackets the re-key in the same snap-parity
    # open/close the push path uses, so a straddling replica pull can
    # never revalidate; the 'silent' configuration re-keys without it.
    if p['sphase'] == 'swapopen':
        def swopen(m2, n=n):
            m2['counters']['snap/%s' % n] = \
                m2['counters'].get('snap/%s' % n, 0) + 1
            m2['procs'][n]['sphase'] = 'rekeyA'
        return [(n, 'snap parity goes ODD for the epoch-swap re-key',
                 swopen)]
    if p['sphase'] == 'rekeyA':
        def rekey_a(m2, n=n):
            m2['kv']['lay/A'] = 2
            m2['procs'][n]['sphase'] = 'rekeyB'
        return [(n, 're-keys tensor A under the new plan (layout 2)',
                 rekey_a)]
    if p['sphase'] == 'rekeyB':
        def rekey_b(m2, n=n):
            p2 = m2['procs'][n]
            m2['kv']['lay/B'] = 2
            if cfg.swap_parity == 'bump':
                p2['sphase'] = 'swapclose'
            else:
                p2['status'] = 'done'
        return [(n, 're-keys tensor B under the new plan (layout 2)',
                 rekey_b)]
    # 'swapclose': parity returns even, the swap is committed
    def swclose(m2, n=n):
        m2['counters']['snap/%s' % n] = \
            m2['counters'].get('snap/%s' % n, 0) + 1
        m2['procs'][n]['status'] = 'done'
    return [(n, 'snap parity returns EVEN after the re-key', swclose)]


def _sreader_transitions(m, cfg, n, p):
    """A non-voting serving replica pulling one multi-tensor snapshot.

    'pin_then_read' (HEAD): pin the parity sum + published floor while
    even, read tensor A, read tensor B, then REVALIDATE — accept only
    if the parity sum is unchanged (the monotone counter makes
    'unchanged' mean 'no write landed'), else retry from the pin. A
    parity stuck odd with every trainer dead is the crashed-writer
    case: the replica gives up this pull and keeps its previous
    snapshot (it must never stall, and must never accept the torn
    round).

    'read_then_pin' (the seeded tempting-but-wrong ordering): read the
    tensors FIRST, then read the parity/step once and stamp the
    snapshot if even — a trainer completing a whole round between the
    two tensor reads leaves the parity even again, so the mixed
    snapshot is accepted undetectably."""
    writers = sorted(w for w in m['procs']
                     if m['procs'][w]['role'] == 'swriter')

    def writer_live(m2):
        return any(m2['procs'][w]['status'] in ('running', 'stalled')
                   for w in writers)

    def accept(m2, n, pinned_step):
        p2 = m2['procs'][n]
        if p2.get('lay_a', 1) != p2.get('lay_b', 1):
            _set_violation(
                m2, 'swap-torn-snapshot',
                'replica %s ACCEPTED a snapshot straddling the '
                'epoch-swap re-key: tensor A carries shard layout %d, '
                'tensor B layout %d — with overlapping key names of '
                'different geometry the merged value is garbage, and '
                'the parity revalidation never fired'
                % (n, p2.get('lay_a', 1), p2.get('lay_b', 1)))
        elif p2['saw_a'] != p2['saw_b'] or p2['saw_a'] != pinned_step:
            _set_violation(
                m2, 'mixed-version-snapshot',
                'replica %s ACCEPTED a snapshot stamped step %d whose '
                'tensors carry versions A=%d B=%d — tensor versions '
                'from different published steps served as one '
                'consistent model' % (n, pinned_step, p2['saw_a'],
                                      p2['saw_b']))
        p2['status'] = 'done'

    if cfg.snapshot_order == 'pin_then_read':
        if p['sphase'] == 'pin':
            if _snap_parity(m, writers) % 2:
                if writer_live(m):
                    return []   # a live trainer will close the round
                def give_up(m2, n=n):
                    # crashed-writer-odd-parity: keep the previous
                    # snapshot, end the pull — never block training,
                    # never accept the torn round
                    m2['procs'][n]['status'] = 'done'
                return [(n, 'gives up the pull (trainer died '
                         'mid-round, parity stuck odd); keeps serving '
                         'its previous snapshot', give_up)]
            def pin(m2, n=n):
                p2 = m2['procs'][n]
                p2['pinned_parity'] = _snap_parity(m2, writers)
                p2['pinned_step'] = _snap_floor(m2, writers)
                p2['sphase'] = 'readA'
            return [(n, 'pins parity (even) + published floor', pin)]
        if p['sphase'] == 'readA':
            def read_a(m2, n=n):
                p2 = m2['procs'][n]
                p2['saw_a'] = m2['kv'].get('sv/A', 0)
                p2['lay_a'] = m2['kv'].get('lay/A', 1)
                p2['sphase'] = 'readB'
            return [(n, 'vmget tensor A', read_a)]
        if p['sphase'] == 'readB':
            def read_b(m2, n=n):
                p2 = m2['procs'][n]
                p2['saw_b'] = m2['kv'].get('sv/B', 0)
                p2['lay_b'] = m2['kv'].get('lay/B', 1)
                p2['sphase'] = 'check'
            return [(n, 'vmget tensor B', read_b)]
        # 'check': revalidate the pinned parity
        def check(m2, n=n):
            p2 = m2['procs'][n]
            if _snap_parity(m2, writers) != p2['pinned_parity']:
                p2['sphase'] = 'pin'   # a write landed: retry
                return
            accept(m2, n, p2['pinned_step'])
        return [(n, 'revalidates the parity; accept iff unchanged',
                 check)]

    # read_then_pin: tensors first, one parity/step read after
    if p['sphase'] == 'readA':
        def read_a(m2, n=n):
            p2 = m2['procs'][n]
            p2['saw_a'] = m2['kv'].get('sv/A', 0)
            p2['lay_a'] = m2['kv'].get('lay/A', 1)
            p2['sphase'] = 'readB'
        return [(n, 'vmget tensor A (no pin held)', read_a)]
    if p['sphase'] == 'readB':
        def read_b(m2, n=n):
            p2 = m2['procs'][n]
            p2['saw_b'] = m2['kv'].get('sv/B', 0)
            p2['lay_b'] = m2['kv'].get('lay/B', 1)
            p2['sphase'] = 'pin'
        return [(n, 'vmget tensor B (no pin held)', read_b)]
    # 'pin': one parity/step read stamps the snapshot
    if _snap_parity(m, writers) % 2:
        if not writer_live(m):
            def give_up(m2, n=n):
                m2['procs'][n]['status'] = 'done'
            return [(n, 'gives up the pull (trainer died mid-round)',
                     give_up)]
        def retry(m2, n=n):
            m2['procs'][n]['sphase'] = 'readA'
        return [(n, 'parity odd at stamp time: rereads the tensors',
                 retry)]
    def stamp(m2, n=n):
        accept(m2, n, _snap_floor(m2, writers))
    return [(n, 'parity even at stamp time: accepts the snapshot',
             stamp)]


# -- telemetry cursor ------------------------------------------------------

def _tpusher_transitions(m, cfg, n, p):
    """push_records: the atomic counter bump lands BEFORE the tensor
    write — two transitions, the real race window."""
    if p['tphase'] == 'bump':
        def bump(m2, n=n):
            m2['counters']['tb'] = m2['counters'].get('tb', 0) + 1
            m2['procs'][n]['tphase'] = 'write'
        return [(n, 'push_records: bumps the batch counter (seq %d)'
                 % (p['bseq'] + 1), bump)]

    def write(m2, n=n):
        p2 = m2['procs'][n]
        p2['bseq'] += 1
        m2['kv']['b%d' % p2['bseq']] = 'landed'
        if p2['bseq'] >= p2['batches']:
            p2['status'] = 'done'
        else:
            p2['tphase'] = 'bump'
    return [(n, 'push_records: batch b%d bytes land' % (p['bseq'] + 1),
             write)]


def _collector_transitions(m, cfg, n, p):
    """collect_new_records: read the counter, fetch cursor+1..n; the
    advance rule is configuration. Mid-run polls are budgeted; the
    close-time final sweep is enabled once the pusher is gone (close()
    flushes and collects after joining the push lane)."""
    pushers = [w for w in m['procs']
               if m['procs'][w]['role'] == 'tpusher']
    pusher_live = any(m['procs'][w]['status'] in ('running', 'stalled')
                      for w in pushers)

    def poll(m2, final, n=n):
        p2 = m2['procs'][n]
        cnt = m2['counters'].get('tb', 0)
        consumed = p2['cursor']
        for seq in range(p2['cursor'] + 1, cnt + 1):
            if ('b%d' % seq) in m2['kv']:
                consumed = seq
                m2['kv']['consumed/b%d' % seq] = '1'
            else:
                # counter-bumped but not yet written
                if cfg.cursor_advance == 'decoded_prefix':
                    break   # retry from here next poll
                consumed = seq   # pre-PR 11: skip it forever
        p2['cursor'] = consumed
        if final:
            p2['status'] = 'done'

    ts = []
    if p['polls_left'] > 0:
        def midpoll(m2, n=n):
            m2['procs'][n]['polls_left'] -= 1
            poll(m2, final=False)
        ts.append((n, 'monitor poll (reads counter, fetches new '
                   'batches)', midpoll))
    if not pusher_live:
        def finalpoll(m2, n=n):
            poll(m2, final=True)
        ts.append((n, 'close-time final sweep', finalpoll))
    return ts


def _telemetry_terminal_check(m):
    """The no-permanent-skip invariant: every batch whose bytes landed
    must have been consumed by the final sweep."""
    problems = []
    for k in sorted(m['kv']):
        if k.startswith('b') and not k.startswith('b/') and \
                m['kv'][k] == 'landed' and \
                ('consumed/' + k) not in m['kv']:
            problems.append((
                'cursor-skip',
                'batch %s landed (decodable) but the cursor skipped '
                'it permanently — a poll racing the in-flight push '
                'advanced past the gap and never came back' % k))
    return problems


# -- dispatch + stuck diagnosis -------------------------------------------

_ROLES = {'dwriter': _writer_transitions,
          'mwriter': _malformed_transitions,
          'dreader': _reader_transitions,
          'fencer': _fencer_transitions,
          'pworker': _pipe_transitions,
          'lworker': _lworker_transitions,
          'swriter': _swriter_transitions,
          'sreader': _sreader_transitions,
          'tpusher': _tpusher_transitions,
          'collector': _collector_transitions}


def proc_transitions(m, cfg, n):
    p = m['procs'][n]
    if p['status'] != 'running':
        return []
    return _ROLES[p['role']](m, cfg, n, p)


def describe_stuck(m):
    """Stall diagnosis for data-plane states: name any reader wedged
    on odd parity (the PR 5 symptom) the way the admit-inversion
    diagnosis names the invisible frozen counter."""
    lines = []
    for n in sorted(m['procs']):
        p = m['procs'][n]
        if p['status'] not in ('running', 'stalled'):
            continue
        if p['role'] == 'dreader':
            key = p['tkey']
            owners = sorted(
                k.split('/')[2] for k in m['kv']
                if k.startswith('seq/%s/' % key))
            dead = [w for w in owners
                    if m['procs'][w]['status'] in ('crashed', 'failed')]
            if _t_open(m, key) > 0 and dead:
                lines.append(
                    'reader %s is WEDGED on key %s: version parity is '
                    'stuck odd (open_writes=%d) because writer %s '
                    'died mid-sequence and nothing aborted its open '
                    'chunk sequence — every retry reads odd parity '
                    'until a DELNS' % (n, key, _t_open(m, key),
                                       ','.join(dead)))
                continue
        if p['role'] == 'pworker':
            lines.append(
                'worker %s is blocked at the step-%d gate'
                % (n, p['step']))
            continue
        if p['role'] == 'lworker':
            lines.append(
                'worker %s is blocked at the round-%d gate (floors '
                'are published in sync rounds; a step-scoped gate '
                'target can never be met)' % (n, p['round']))
            continue
        if p['role'] == 'sreader':
            lines.append(
                'serving replica %s is blocked pinning a snapshot: '
                'the snap parity is stuck odd and no give-up '
                'transition fired' % n)
            continue
        lines.append('%s is %s (role %s) with no enabled transition'
                     % (n, p['status'], p['role']))
    return '; '.join(lines) or 'no live process has an enabled ' \
                               'transition'


# -- scenario construction ------------------------------------------------

def _base(procs, crash_budget=0):
    return {'counters': {}, 'kv': {}, 'procs': procs,
            'slot_owner': {}, 'crash_budget': crash_budget,
            'violation': None}


def _writer(n, key, writes=1, sparse=False):
    return {'role': 'dwriter', 'status': 'running', 'tkey': key,
            'wphase': 'w0', 'wseq': 1, 'writes': writes,
            'sparse': sparse, 'fence_key': 'fence/' + n,
            'fence_gen': 0, 'stall_budget': 0}


def _reader(n, key):
    return {'role': 'dreader', 'status': 'running', 'tkey': key,
            'rphase': 'r0', 'ver0': 0, 'saw0': '', 'stall_budget': 0}


def _scenario(name, cfg, model, **kw):
    kw.setdefault('transitions_fn', proc_transitions)
    kw.setdefault('describe_stuck', describe_stuck)
    kw.setdefault('on_crash',
                  lambda m, n: disconnect_abort(m, cfg, n))
    return Scenario(name, cfg, model, **kw)


def torn_write_scenario(cfg):
    """One chunked writer, one malformed writer whose offset-0 frame
    is rejected mid-flight, one versioned reader. PR 1's any-frame
    abort must resurface as a torn-read-clean counterexample here."""
    procs = {'A': _writer('A', 'T'),
             'M': {'role': 'mwriter', 'status': 'running', 'tkey': 'T',
                   'stall_budget': 0},
             'R': _reader('R', 'T')}
    return _scenario('torn_write', cfg, _base(procs))


def writer_death_scenario(cfg):
    """A chunked writer that may crash between any two frames (the
    died-mid-push case every failure policy must survive) and a
    versioned reader. PR 5's missing disconnect abort must resurface
    as a stall naming the wedged reader."""
    procs = {'A': _writer('A', 'T'), 'R': _reader('R', 'T')}
    return _scenario('writer_death', cfg, _base(procs, crash_budget=1),
                     crashable=('A',))


def zombie_sparse_scenario(cfg):
    """A row-sparse (BSADD) writer stalls mid-sequence, is declared
    dead and fenced by the exclude path, then resumes its in-flight
    final frame. HEAD's under-tensor-lock re-check must reject it
    (and abort the sequence so the reader is not wedged); the
    entry-only check lets the zombie frame commit."""
    procs = {'A': _writer('A', 'T', sparse=True),
             'E': {'role': 'fencer', 'status': 'running', 'target': 'A',
                   'bumped': False, 'stall_budget': 0},
             'R': _reader('R', 'T')}
    return _scenario('zombie_sparse', cfg, _base(procs),
                     stallable=('A',))


def pipeline_scenario(cfg):
    """Two loose-mode workers at pipeline depth 2 training
    ``cfg.steps`` gated steps. The prefetch peer-floor guard and the
    floor-scan position are the configuration under test; the
    invariant is the serial staleness bound."""
    procs = {}
    for n in ('w0', 'w1'):
        procs[n] = {'role': 'pworker', 'status': 'running', 'step': 1,
                    'pphase': 'gate', 'pf_floor': -1, 'pf_seen': (),
                    'stall_budget': 0}
    return _scenario('pipeline', cfg, _base(procs))


def telemetry_scenario(cfg):
    """One span pusher (counter bump and batch write as separate
    transitions, crashable between them) and the monitor's
    incremental-cursor collector with budgeted mid-run polls plus the
    close-time final sweep. PR 11's counter-advance rule must
    resurface as a cursor-skip counterexample."""
    procs = {'P': {'role': 'tpusher', 'status': 'running',
                   'tphase': 'bump', 'bseq': 0, 'batches': 2,
                   'stall_budget': 0},
             'C': {'role': 'collector', 'status': 'running',
                   'cursor': 0, 'polls_left': cfg.polls,
                   'stall_budget': 0}}
    return _scenario('telemetry', cfg, _base(procs, crash_budget=1),
                     crashable=('P',),
                     terminal_check=_telemetry_terminal_check)


def local_sgd_scenario(cfg):
    """Two loose-mode workers under local-SGD ``H = cfg.local_steps``
    training ``cfg.steps`` sync rounds. Proves the H-step staleness
    bound (no pull observes peer state older than H x gate_staleness
    train steps) and the window-merge invariant (the PS total is the
    MEAN of the pushed windows); the sum-not-average push and the
    step-scoped gate are the pinned counterexamples."""
    procs = {}
    for n in ('w0', 'w1'):
        procs[n] = {'role': 'lworker', 'status': 'running', 'round': 1,
                    'lphase': 'gate', 'lstep': 0,
                    'h': cfg.local_steps, 'stall_budget': 0}
    return _scenario('local_sgd', cfg, _base(procs),
                     terminal_check=_local_sgd_terminal_check)


def reader_fleet_scenario(cfg):
    """One trainer publishing ``cfg.steps`` seqlock-guarded rounds
    (crashable mid-round — the parity-stuck-odd case) against two
    non-voting serving replicas each pulling one two-tensor snapshot;
    replica R0 is itself crashable (a reader killed mid-pull must be
    harmless). The replica's snapshot ordering is the configuration;
    the invariant is that no ACCEPTED snapshot mixes tensor versions
    from different published steps."""
    procs = {'W': {'role': 'swriter', 'status': 'running', 'round': 1,
                   'sphase': 'open', 'rounds': cfg.steps,
                   'stall_budget': 0}}
    first = ('pin' if cfg.snapshot_order == 'pin_then_read'
             else 'readA')
    for n in ('R0', 'R1'):
        procs[n] = {'role': 'sreader', 'status': 'running',
                    'sphase': first, 'pinned_parity': -1,
                    'pinned_step': -1, 'saw_a': -1, 'saw_b': -1,
                    'stall_budget': 0}
    return _scenario('reader_fleet', cfg, _base(procs, crash_budget=1),
                     crashable=('W', 'R0'))


def reader_fleet_swap_scenario(cfg):
    """The reader fleet across an epoch-swap boundary (PR 19): one
    trainer publishes a seqlock-guarded round and then APPLIES an
    armed epoch swap — re-keying both tensors under the new plan
    (values moved, layouts changed) — while two serving replicas pull
    snapshots; the trainer may crash mid-swap. ``cfg.swap_parity`` is
    the configuration under test: HEAD's open/close bracket around the
    re-key forces any straddling pull to fail revalidation (or give up
    on a mid-swap death), while the silent re-key lets a replica
    accept a snapshot mixing the two shard layouts. One reader: the
    mixed-layout property is local to a single replica's
    pin -> read -> revalidate cycle (multi-reader independence is
    reader_fleet's job), and the second reader only multiplies the
    interleaving product without new orderings."""
    procs = {'W': {'role': 'swriter', 'status': 'running', 'round': 1,
                   'sphase': 'open', 'rounds': 1, 'swap_pending': True,
                   'stall_budget': 0}}
    first = ('pin' if cfg.snapshot_order == 'pin_then_read'
             else 'readA')
    for n in ('R0',):
        procs[n] = {'role': 'sreader', 'status': 'running',
                    'sphase': first, 'pinned_parity': -1,
                    'pinned_step': -1, 'saw_a': -1, 'saw_b': -1,
                    'stall_budget': 0}
    return _scenario('reader_fleet_swap', cfg,
                     _base(procs, crash_budget=1), crashable=('W',))


def scenarios(cfg):
    """The standard data-plane scenario suite for one configuration."""
    return [torn_write_scenario(cfg), writer_death_scenario(cfg),
            zombie_sparse_scenario(cfg), pipeline_scenario(cfg),
            telemetry_scenario(cfg), local_sgd_scenario(cfg),
            reader_fleet_scenario(cfg),
            reader_fleet_swap_scenario(cfg)]


#: Each seeded pre-fix ordering must yield its counterexample in the
#: named scenario — the sensitivity guard, exactly like the
#: control-plane checker's (PR4_RESURRECTION et al.).
SEEDED_BUGS = (
    ('PR1 offset-0 abort closes another writer\'s sequence',
     PR1_OFFSET0_ABORT, 'torn_write', 'torn-read-clean'),
    ('PR5 disconnect leaves the sequence open (reader wedge)',
     PR5_DISCONNECT_WEDGE, 'writer_death', 'stall'),
    ('PR11 cursor advances past an in-flight batch',
     PR11_CURSOR_RACE, 'telemetry', 'cursor-skip'),
    ('fence checked at wire entry only (zombie frame commits)',
     UNLOCKED_FENCE_RECHECK, 'zombie_sparse', 'zombie-frame-commit'),
    ('prefetch served without the peer-floor discard',
     NO_FLOOR_DISCARD, 'pipeline', 'stale-prefetch'),
    ('peer floor scanned after the pull-ahead it must lower-bound',
     FLOOR_AFTER_PULL, 'pipeline', 'stale-prefetch'),
    ('local-SGD window pushed as SUM not average (W-fold overshoot)',
     LOCAL_SGD_SUM, 'local_sgd', 'window-sum-divergence'),
    ('local-SGD gate target scoped to train steps, not sync rounds',
     LOCAL_SGD_STEP_GATE, 'local_sgd', 'stall'),
    ('snapshot tensors read before the step is pinned (mixed-version '
     'serve)', SNAPSHOT_READ_BEFORE_PIN, 'reader_fleet',
     'mixed-version-snapshot'),
    ('epoch-swap re-key without the snap-parity bracket (straddling '
     'replica accepts mixed shard layouts)', SWAP_SILENT_REKEY,
     'reader_fleet_swap', 'swap-torn-snapshot'),
)

#: Exploration statistics of the last :func:`analyze` run.
LAST_STATS = {}


def analyze():
    """The data-plane analyzer: HEAD explores clean on every scenario
    AND every seeded pre-fix ordering still counterexamples. Returns
    finding strings (empty = clean)."""
    from autodist_tpu.analysis import explore
    LAST_STATS.clear()
    return explore.run_suite(HEAD, scenarios, SEEDED_BUGS,
                             'data-plane model', stats=LAST_STATS)
