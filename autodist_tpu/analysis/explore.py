"""Bounded exhaustive exploration of the protocol model.

Breadth-first enumeration of EVERY interleaving of the scenario's
process transitions, plus explorer-injected crashes (budgeted) and
stall/resume pairs, with state memoization. Two property classes:

- **safety**: a transition that sets ``model['violation']``
  (fenced-write-commit, resurrection — see
  :mod:`~autodist_tpu.analysis.protocol_model`) terminates its branch
  and is reported with the exact event path that reached it;
- **liveness**: after the full reachable graph is built, a backward
  reachability pass from the good terminal states (every process done/
  crashed/failed, scenario terminal invariants clean) finds states
  from which NO good terminal is reachable — a stall. The shortest
  path to one is reported with a diagnosis of what is wedged,
  including any invisible frozen counter in the gate's prefix-min.

Counterexamples print as readable event sequences
(:func:`format_violation`), which is how the two seeded historical
bugs surface in ``tests/test_analysis.py``.
"""
from collections import deque
from dataclasses import dataclass, field

from autodist_tpu.analysis import protocol_model as pm


@dataclass
class Violation:
    kind: str
    trace: tuple          # ((actor, label), ...)
    diagnosis: str


@dataclass
class Result:
    scenario: str
    ok: bool
    violations: list = field(default_factory=list)
    states: int = 0
    terminals: int = 0

    def kinds(self):
        return sorted({v.kind for v in self.violations})


def _copy(m):
    return {'counters': dict(m['counters']), 'kv': dict(m['kv']),
            'procs': {n: dict(p) for n, p in m['procs'].items()},
            'slot_owner': dict(m['slot_owner']),
            'crash_budget': m['crash_budget'],
            'violation': m['violation']}


def _freeze(m):
    return (tuple(sorted(m['counters'].items())),
            tuple(sorted(m['kv'].items())),
            tuple(sorted((n, tuple(sorted(p.items())))
                         for n, p in m['procs'].items())),
            tuple(sorted(m['slot_owner'].items())),
            m['crash_budget'], m['violation'])


def _transitions(m, sc):
    ts = []
    for n in sorted(m['procs']):
        p = m['procs'][n]
        if p['status'] == 'running':
            ts.extend(sc.transitions_fn(m, sc.cfg, n))
        elif p['status'] == 'stalled':
            def resume(m2, n=n):
                m2['procs'][n]['status'] = 'running'
            ts.append((n, 'resumes (was stalled)', resume))
    if m['crash_budget'] > 0:
        for n in sc.crashable:
            if m['procs'][n]['status'] in ('running', 'stalled'):
                def crash(m2, n=n):
                    m2['procs'][n]['status'] = 'crashed'
                    m2['crash_budget'] -= 1
                    # model-specific death side effects (e.g. the
                    # service's disconnect-time SeqAborter: a dead
                    # connection's open chunk sequences are aborted)
                    if sc.on_crash is not None:
                        sc.on_crash(m2, n)
                ts.append((n, 'CRASHES', crash))
    for n in sc.stallable:
        p = m['procs'][n]
        if p['status'] == 'running' and p.get('stall_budget', 0) == 0:
            def stall(m2, n=n):
                m2['procs'][n]['status'] = 'stalled'
                m2['procs'][n]['stall_budget'] = 1
            ts.append((n, 'stalls (slow past the heartbeat timeout)',
                       stall))
    return ts


def _terminal_good(m):
    return all(p['status'] in ('done', 'crashed', 'failed')
               for p in m['procs'].values())


def _path(parents, key):
    events = []
    while parents[key] is not None:
        key, actor, label = parents[key]
        events.append((actor, label))
    events.reverse()
    return tuple(events)


def _describe_stuck(m):
    lines = []
    for n in sorted(m['procs']):
        p = m['procs'][n]
        if p['status'] not in ('running', 'stalled'):
            continue
        if p['role'] == 'worker' and p['phase'] == 'gate':
            steps = {k[len('step/'):]: v
                     for k, v in m['counters'].items()
                     if k.startswith('step/')}
            k = p['world_seen'] - len(p['excluded'])
            lines.append(
                '%s is blocked at the step-%d gate: needs >= %d step '
                'counters with min >= %d, plane has %s'
                % (n, p['step'], k, p['step'], steps))
        else:
            lines.append('%s is %s (role %s) with no enabled '
                         'transition' % (n, p['status'], p['role']))
    live_views = [p for p in m['procs'].values()
                  if p['status'] in ('running', 'stalled')
                  and p['role'] == 'worker']
    for key, v in sorted(m['counters'].items()):
        if not key.startswith('step/') or v >= pm.SENTINEL:
            continue
        w = key[len('step/'):]
        owner = m['slot_owner'].get(w)
        status = m['procs'][owner]['status'] if owner else 'unknown'
        if status not in ('crashed', 'failed'):
            continue
        visible = any(int(w[1:]) < p['world_seen'] for p in live_views)
        if not visible:
            lines.append(
                '%s=%d belongs to %s %s, which is in NO survivor\'s '
                'membership view (the epoch was never bumped for it): '
                'an invisible frozen counter in the gate\'s prefix-min '
                'that no exclusion can ever release' % (key, v, status,
                                                        owner or w))
    return '; '.join(lines) or 'no live process has an enabled ' \
                               'transition'


def explore(sc, max_states=500000):
    """Exhaustively explore ``sc`` and return a :class:`Result`."""
    init = _copy(sc.model)
    k0 = _freeze(init)
    states = {k0: init}
    parents = {k0: None}
    edges = {}
    queue = deque([k0])
    violations = {}
    terminal_good = []
    terminal_bad = []   # terminal, but a terminal invariant failed
    violated = []       # branch ended in a mid-run violation
    dead_ends = []
    while queue:
        k = queue.popleft()
        m = states[k]
        if m['violation'] is not None:
            kind, msg = m['violation']
            if kind not in violations:
                violations[kind] = Violation(kind, _path(parents, k),
                                             msg)
            violated.append(k)
            edges[k] = []
            continue
        ts = _transitions(m, sc)
        if not ts:
            edges[k] = []
            if _terminal_good(m):
                ok = True
                for kind, msg in (sc.terminal_check(m)
                                  if sc.terminal_check else []):
                    ok = False
                    if kind not in violations:
                        violations[kind] = Violation(
                            kind, _path(parents, k), msg)
                if ok:
                    terminal_good.append(k)
                else:
                    terminal_bad.append(k)
            else:
                dead_ends.append(k)
            continue
        outs = []
        for actor, label, fn in ts:
            m2 = _copy(m)
            fn(m2)
            k2 = _freeze(m2)
            if k2 not in states:
                states[k2] = m2
                parents[k2] = (k, actor, label)
                queue.append(k2)
            outs.append(k2)
        edges[k] = outs
        if len(states) > max_states:
            raise RuntimeError(
                'scenario %r exceeded %d states — the model must stay '
                'small-scope' % (sc.name, max_states))
    # liveness: backward reachability over terminals. Bad terminals
    # and mid-run violation states (both reported above) seed it too —
    # a branch that ended in a reported counterexample is not ALSO a
    # stall, and must not produce a second counterexample with a
    # misleading diagnosis.
    if 'stall' not in violations:
        rev = {}
        for src, outs in edges.items():
            for dst in outs:
                rev.setdefault(dst, []).append(src)
        coreach = set(terminal_good) | set(terminal_bad) | \
            set(violated)
        bq = deque(coreach)
        while bq:
            k = bq.popleft()
            for src in rev.get(k, []):
                if src not in coreach:
                    coreach.add(src)
                    bq.append(src)
        stuck = [k for k in dead_ends if k not in coreach] or \
                [k for k in states
                 if k not in coreach and states[k]['violation'] is None]
        if stuck:
            # BFS insertion order makes parents-paths shortest; take
            # the earliest-discovered stuck state for the tightest trace
            k = min(stuck, key=lambda k: len(_path(parents, k)))
            describe = sc.describe_stuck or _describe_stuck
            violations['stall'] = Violation(
                'stall', _path(parents, k),
                'no good terminal state is reachable from here: ' +
                describe(states[k]))
    vs = sorted(violations.values(), key=lambda v: v.kind)
    return Result(scenario=sc.name, ok=not vs, violations=vs,
                  states=len(states), terminals=len(terminal_good))


def check_all(cfg, max_states=500000):
    """Explore the standard scenario suite under ``cfg``."""
    return [explore(sc, max_states=max_states)
            for sc in pm.scenarios(cfg)]


def format_violation(result, v):
    """A counterexample as a readable numbered event sequence."""
    lines = ['counterexample [%s] in scenario %r:' % (v.kind,
                                                      result.scenario)]
    for i, (actor, label) in enumerate(v.trace, 1):
        lines.append('  %2d. %-4s %s' % (i, actor + ':', label))
    lines.append('  => ' + v.diagnosis)
    return '\n'.join(lines)


#: The negative self-tests: each seeded pre-fix ordering must yield a
#: counterexample in the named scenario with the named violation kind.
#: If the model ever stops re-deriving a historical bug, it has lost
#: the sensitivity that justifies trusting its clean HEAD run.
SEEDED_BUGS = (
    ('PR4 delete-release resurrection', pm.PR4_RESURRECTION,
     'exclude', 'resurrection'),
    ('PR6 admit publish-before-epoch inversion',
     pm.PR6_ADMIT_INVERSION, 'admit', 'stall'),
    ('unfenced exclude (claim observable before fence)',
     pm.UNFENCED_EXCLUDE, 'zombie', 'fenced-write-commit'),
    ('cap-raced join slot abandoned un-retired',
     pm.UNRETIRED_CAP_RACE, 'cap_race', 'cap-slot-unretired'),
)


#: Exploration statistics of the last :func:`analyze` run (or any
#: model-checker pass using :func:`run_suite`): per-scenario and total
#: states explored, so ``tools/analyze.py --json`` can report model
#: cost and ``bench_compare`` can flag state-space blowup.
LAST_STATS = {}


def run_suite(head_cfg, scenarios_fn, seeded, label, stats=None,
              max_states=500000):
    """The shared both-directions analyzer every model checker runs:
    the HEAD configuration must explore clean across the whole
    scenario suite, AND every seeded pre-fix ordering must still
    produce its counterexample (the sensitivity guard). ``seeded`` is
    an iterable of ``(name, cfg, scenario_name, violation_kind)``.
    Fills ``stats`` (a dict) with per-scenario/total states explored.
    Returns finding strings (empty = clean)."""
    findings = []
    per_scenario = {}
    for sc in scenarios_fn(head_cfg):
        result = explore(sc, max_states=max_states)
        per_scenario[sc.name] = result.states
        for v in result.violations:
            findings.append(
                '%s: HEAD ordering has a counterexample (%s)\n%s'
                % (label, v.kind, format_violation(result, v)))
    for name, cfg, scen_name, kind in seeded:
        sc = {s.name: s for s in scenarios_fn(cfg)}[scen_name]
        result = explore(sc, max_states=max_states)
        # unique stats key per seeded exploration: two seeds sharing a
        # scenario+kind (e.g. both pipeline floor bugs) must both show
        # up, or a state-space blowup in the second is invisible to
        # the bench_compare gate these counts feed
        key = '%s[%s]' % (scen_name, kind)
        while key in per_scenario:
            key += "'"
        per_scenario[key] = result.states
        if kind not in result.kinds():
            findings.append(
                '%s: seeded bug %r no longer yields a %r '
                'counterexample in scenario %r (found: %s) — the model '
                'lost the sensitivity that justifies its clean HEAD '
                'run' % (label, name, kind, scen_name,
                         result.kinds() or 'none'))
    if stats is not None:
        stats['scenarios'] = per_scenario
        stats['states_explored'] = sum(per_scenario.values())
    return findings


def analyze():
    """The protocol-model analyzer: HEAD's orderings must explore clean
    across the whole scenario suite, AND every seeded pre-fix ordering
    must still produce its counterexample. Returns finding strings
    (empty = clean)."""
    LAST_STATS.clear()
    return run_suite(pm.HEAD, pm.scenarios, SEEDED_BUGS,
                     'protocol model', stats=LAST_STATS)
