"""Env-knob lint: no undeclared ``AUTODIST_*`` reads, no silently
unforwarded knobs, no docs drift.

Three invariants over the whole tree:

1. **Declaration** — every ``AUTODIST_*`` environment read (Python
   ``os.environ[...]``/``os.environ.get``/``os.getenv``, C++
   ``getenv``) must name a member of ``const.py``'s typed ENV
   registry, or carry an explicit entry in :data:`ALLOWED_RAW_READS`
   with a reason. A raw read of an undeclared name is a knob with no
   validation, no documentation surface and no forwarding decision —
   exactly how ``AUTODIST_FUSED_CONV`` and ``AUTODIST_PP_STASH_LIMIT_MB``
   lived unregistered for several PRs.
2. **Forwarding** — every ENV member must either ride the
   coordinator's ``_FORWARDED_FLAGS`` (worker-affecting knobs reach
   every launched worker) or appear in :data:`FORWARD_EXEMPT` with the
   reason it deliberately does not (per-worker identity, chief-side
   only, security transport, explicit-install chaos knobs). A knob in
   neither set is a finding: an operator exporting it on the chief
   would silently configure only the chief.
3. **Documentation** — every ``AUTODIST_*`` ENV member must be
   mentioned somewhere under ``docs/`` (the generated ``docs/api/``
   pages don't count: they mirror docstrings, so they can't catch a
   knob the hand-written docs forgot — ``docs/usage/env-knobs.md`` is
   the catch-all reference), and a choice-validated knob
   (``_choice`` in const.py, e.g. ``AUTODIST_STRAGGLER_POLICY``) must
   enumerate the SAME choice set in the docs near its mention —
   findings name the knob and the missing/stale side.

Writes (``os.environ[k] = v``, ``.setdefault``, ``.pop``, ``del``,
``monkeypatch.setenv``) are not reads and are ignored.
"""
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Scanned roots, relative to the repo.
SCAN_ROOTS = ('autodist_tpu', 'tools', 'tests', 'examples', 'bench.py',
              '__graft_entry__.py')

#: Undeclared raw reads allowed, with the reason. Empty on HEAD: every
#: knob the tree reads is registered. Add entries only for names that
#: deliberately must not enter the registry (none known today).
ALLOWED_RAW_READS = {}

#: ENV members that deliberately do NOT ride ``_FORWARDED_FLAGS``,
#: with the reason. Everything else must be forwarded.
FORWARD_EXEMPT = {
    'AUTODIST_WORKER':
        'per-worker identity, set explicitly by Coordinator._worker_env',
    'AUTODIST_STRATEGY_ID':
        'per-launch value, set explicitly by Coordinator._worker_env',
    'AUTODIST_PROCESS_ID':
        'per-worker identity, set explicitly by Coordinator._worker_env',
    'AUTODIST_NUM_PROCESSES':
        'per-launch value, set explicitly by Coordinator._worker_env',
    'AUTODIST_COORDINATOR_ADDR':
        'per-launch value, set explicitly by Coordinator._worker_env',
    'AUTODIST_RUN_ID':
        'per-launch nonce, issued and set explicitly by the launcher',
    'AUTODIST_DEBUG_REMOTE':
        'chief-side launcher behavior (print instead of ssh)',
    'AUTODIST_DUMP_GRAPHS':
        'per-process debug dumps; divergence is harmless',
    'AUTODIST_COORD_TOKEN':
        'deliberately not forwarded: env assignments ride the remote '
        'ssh command line (world-readable in ps); the secret ships as '
        'a mode-0600 file via AUTODIST_COORD_TOKEN_FILE instead',
    'AUTODIST_COORD_TOKEN_FILE':
        'set explicitly per worker after the token file is copied',
    'AUTODIST_ELASTIC_JOIN':
        'set per joiner by Coordinator.scale_up, never on the launch '
        'cohort',
    'AUTODIST_AUTO_CHECKPOINT_EVERY':
        'chief-side checkpoint backstop; workers never act on it',
    'AUTODIST_FAULT_PLAN':
        'chaos-only: honored only where a FaultLine is explicitly '
        'installed; production sessions never read it',
    'AUTODIST_STRAGGLER_POLICY':
        'chief-side monitor verdict policy: workers only emit spans '
        '(AUTODIST_TELEMETRY is forwarded) and never act on verdicts',
    'AUTODIST_MONITOR_WINDOW':
        'chief-side monitor statistics window; no worker reads it',
    'AUTODIST_RECALIBRATE_EVERY':
        "chief-side recalibration cadence; the refit constants feed "
        "only the chief's re-rank",
}

_PY_READ = re.compile(
    r'''os\.environ\.get\(\s*['"](AUTODIST_\w+)['"]'''
    r'''|os\.getenv\(\s*['"](AUTODIST_\w+)['"]'''
    r'''|(?<!del )os\.environ\[['"](AUTODIST_\w+)['"]\](?![ \t]*=[^=])''')
_CC_READ = re.compile(r'getenv\("(AUTODIST_\w+)"\)')


def _iter_sources():
    for root in SCAN_ROOTS:
        path = os.path.join(REPO, root)
        if os.path.isfile(path):
            yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames
                           if d not in ('__pycache__', '.git')]
            for fn in filenames:
                if fn.endswith(('.py', '.cc', '.h')):
                    yield os.path.join(dirpath, fn)


def raw_reads(files=None):
    """``[(relpath, lineno, name)]`` for every AUTODIST_* env read.

    Scans whole-file text (not per-line) so a call wrapped across lines
    for the 72-column style — ``os.environ.get(\\n    'AUTODIST_X')`` —
    still matches."""
    out = []
    own = os.path.abspath(__file__)
    for path in (files if files is not None else _iter_sources()):
        if os.path.abspath(path) == own:
            continue   # this module's own regex literals are not reads
        pat = _CC_READ if path.endswith(('.cc', '.h')) else _PY_READ
        with open(path, encoding='utf-8', errors='replace') as f:
            text = f.read()
        for m in pat.finditer(text):
            name = next(g for g in m.groups() if g)
            out.append((os.path.relpath(path, REPO),
                        text.count('\n', 0, m.start()) + 1, name))
    return out


def declared_env():
    from autodist_tpu.const import ENV
    return {e.name for e in ENV}


#: Hand-written docs roots the documentation invariant scans;
#: ``docs/api`` is excluded on purpose (generated from docstrings —
#: it cannot catch a knob the written docs forgot).
DOCS_EXCLUDE = ('api',)


def docs_text(root=None):
    """Concatenated hand-written docs (``docs/**/*.md|rst`` minus the
    generated API pages)."""
    root = root or os.path.join(REPO, 'docs')
    chunks = []
    for dirpath, dirnames, filenames in os.walk(root):
        if dirpath == root:
            # only the TOP-LEVEL docs/api is generated; a hand-written
            # nested dir that happens to be named 'api' still counts
            dirnames[:] = [d for d in dirnames if d not in DOCS_EXCLUDE]
        for fn in sorted(filenames):
            if fn.endswith(('.md', '.rst')):
                with open(os.path.join(dirpath, fn),
                          encoding='utf-8', errors='replace') as f:
                    chunks.append(f.read())
    return '\n'.join(chunks)


def choice_sets(src=None):
    """``{knob: (choices...)}`` for every ``_choice``-validated ENV
    member, parsed from const.py's AST (robust to quoting, the lambda
    parameter name, and call formatting — a regex here once meant a
    reformatted call silently dropped its knob from the invariant).
    A ``_choice`` call whose name or choice tuple is not a static
    literal maps to ``None``, which :func:`check_docs` reports as a
    finding instead of silently skipping the knob."""
    import ast
    if src is None:
        src_path = os.path.join(REPO, 'autodist_tpu', 'const.py')
        with open(src_path, encoding='utf-8') as f:
            src = f.read()
    out = {}
    for node in ast.walk(ast.parse(src)):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == '_choice'):
            continue
        name = node.args[0] if node.args else None
        allowed = node.args[3] if len(node.args) > 3 else None
        name = name.value if (isinstance(name, ast.Constant)
                              and isinstance(name.value, str)) else None
        if allowed is not None and isinstance(
                allowed, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in allowed.elts):
            choices = tuple(e.value for e in allowed.elts)
        else:
            choices = None
        if name is None:
            # a dynamic knob name: surface it under a sentinel so the
            # lint still complains instead of skipping the call
            name = '<dynamic _choice call at line %d>' % node.lineno
            choices = None
        out[name] = choices
    return out


def _doc_windows(docs, knob, radius=700):
    """Text windows around every docs mention of ``knob`` — the
    neighborhood a choice enumeration must live in."""
    wins = []
    for m in re.finditer(re.escape(knob), docs):
        wins.append(docs[max(0, m.start() - radius):
                         m.end() + radius])
    return wins


#: An enumeration-looking token run in PROSE: words separated by ``/``
#: or ``|``, with optional backticks.
_ENUM = re.compile(r'`?(\w+)`?(?:\s*[/|]\s*`?(\w+)`?)+')
#: The same inside one markdown TABLE CELL, where a bare ``|`` is the
#: cell delimiter and a literal pipe separator is escaped as ``\|``.
_ENUM_CELL = re.compile(r'`?(\w+)`?(?:\s*(?:/|\\\|)\s*`?(\w+)`?)+')


def _enum_runs(blob):
    """Enumeration-looking token runs in ``blob``, table-aware: on a
    markdown table row the scan runs per CELL (a bare ``|`` delimits
    cells there, so a run must not chain across the boundary and
    swallow the next cell's first word as a phantom choice)."""
    out = []
    for line in blob.splitlines():
        if line.lstrip().startswith('|'):
            for cell in re.split(r'(?<!\\)\|', line):
                out.extend(m.group(0)
                           for m in _ENUM_CELL.finditer(cell))
        else:
            out.extend(m.group(0) for m in _ENUM.finditer(line))
    return out


def check_docs(declared=None, choices=None, docs=None):
    """The documentation invariant. Returns finding strings (empty =
    clean). ``declared``/``choices``/``docs`` are injectable for
    tests."""
    findings = []
    declared = declared if declared is not None else declared_env()
    choices = choices if choices is not None else choice_sets()
    docs = docs if docs is not None else docs_text()
    for name in sorted(declared):
        if not name.startswith('AUTODIST_'):
            continue    # SYS_* reference-parity paths judged by hand
        # word-bounded: a mention of AUTODIST_TELEMETRY_DIR must not
        # satisfy AUTODIST_TELEMETRY (the registry has real prefix
        # pairs)
        if not re.search(r'\b%s\b' % re.escape(name), docs):
            findings.append(
                'env knob %s is registered in const.py ENV but never '
                'mentioned under docs/ (generated api/ pages '
                'excluded) — missing side: docs '
                '(docs/usage/env-knobs.md is the catch-all reference)'
                % name)
    for knob, allowed in sorted(choices.items()):
        if allowed is None:
            findings.append(
                'choice knob %s: const.py\'s choice set is not a '
                'static literal — the docs-sync invariant cannot '
                'verify it (make the _choice call name the knob and '
                'its tuple of string literals inline)' % knob)
            continue
        wins = _doc_windows(docs, knob)
        if not wins:
            continue    # already reported as undocumented above
        blob = '\n'.join(wins)
        for choice in allowed:
            if not re.search(r'\b%s\b' % re.escape(choice), blob):
                findings.append(
                    'choice knob %s: docs near its mention never name '
                    'the choice %r — missing side: docs (the '
                    'validator in const.py accepts %s)'
                    % (knob, choice, '|'.join(allowed)))
        # a docs enumeration that names 2+ real choices IS the choice
        # list; any extra member of it is stale on the docs side.
        # Judge only enum runs on LINES that mention this knob — the
        # ±700-char windows reach into neighboring knobs' rows, and a
        # neighbor sharing 2+ choice tokens (off/warn/... are common)
        # must not get its own valid choices flagged as this knob's
        # stale ones. One finding per stale token: mention lines can
        # repeat across overlapping windows.
        bound = re.compile(r'\b%s\b' % re.escape(knob))
        knob_lines = '\n'.join(
            ln for ln in blob.splitlines() if bound.search(ln))
        stale = set()
        for run in _enum_runs(knob_lines):
            # only lowercase word tokens can be choice values (knob
            # names and surrounding prose are not), so judge only those
            toks = [t for t in re.split(r'[^\w]+', run)
                    if t and re.fullmatch(r'[a-z][a-z0-9_]*', t)]
            hits = [t for t in toks if t in allowed]
            if len(set(hits)) < 2:
                continue
            stale.update(t for t in toks if t not in allowed)
        for t in sorted(stale):
            findings.append(
                'choice knob %s: docs enumerate choice %r, '
                'which const.py\'s validator does not accept '
                '(%s) — stale side: docs'
                % (knob, t, '|'.join(allowed)))
    return findings


def forwarded_env():
    from autodist_tpu.runtime.coordinator import _FORWARDED_FLAGS
    return {e.name for e in _FORWARDED_FLAGS}


def analyze(files=None):
    """Run all three invariants. Returns finding strings (empty =
    clean)."""
    findings = []
    declared = declared_env()
    for relpath, lineno, name in raw_reads(files):
        if name in declared:
            continue
        if name in ALLOWED_RAW_READS:
            continue
        findings.append(
            '%s:%d: reads undeclared env knob %s — register it in '
            "const.py's ENV (typed, validated, forwardable) or "
            'allowlist it in analysis/env_lint.py with a reason'
            % (relpath, lineno, name))
    for name in sorted(set(ALLOWED_RAW_READS) & declared):
        findings.append(
            'env_lint.ALLOWED_RAW_READS lists %s, which IS declared in '
            "const.py's ENV — stale allowlist entry" % name)
    forwarded = forwarded_env()
    for name in sorted(declared):
        if not name.startswith('AUTODIST_'):
            continue    # SYS_* reference-parity paths judged by hand
        in_fwd = name in forwarded
        in_exempt = name in FORWARD_EXEMPT
        if in_fwd and in_exempt:
            findings.append(
                'env knob %s is BOTH in coordinator._FORWARDED_FLAGS '
                'and env_lint.FORWARD_EXEMPT — resolve the conflict'
                % name)
        elif not in_fwd and not in_exempt:
            findings.append(
                'env knob %s is declared but neither forwarded '
                '(coordinator._FORWARDED_FLAGS) nor exempted with a '
                'reason (env_lint.FORWARD_EXEMPT): an operator '
                'exporting it on the chief silently configures only '
                'the chief' % name)
    for name in sorted(set(FORWARD_EXEMPT) - declared):
        findings.append(
            'env_lint.FORWARD_EXEMPT lists %s, which is not an ENV '
            'member — stale exemption' % name)
    if files is None:   # doctored-source probes lint only their files
        findings.extend(check_docs(declared=declared))
    return findings
