"""Env-knob lint: no undeclared ``AUTODIST_*`` reads, no silently
unforwarded knobs.

Two invariants over the whole tree:

1. **Declaration** — every ``AUTODIST_*`` environment read (Python
   ``os.environ[...]``/``os.environ.get``/``os.getenv``, C++
   ``getenv``) must name a member of ``const.py``'s typed ENV
   registry, or carry an explicit entry in :data:`ALLOWED_RAW_READS`
   with a reason. A raw read of an undeclared name is a knob with no
   validation, no documentation surface and no forwarding decision —
   exactly how ``AUTODIST_FUSED_CONV`` and ``AUTODIST_PP_STASH_LIMIT_MB``
   lived unregistered for several PRs.
2. **Forwarding** — every ENV member must either ride the
   coordinator's ``_FORWARDED_FLAGS`` (worker-affecting knobs reach
   every launched worker) or appear in :data:`FORWARD_EXEMPT` with the
   reason it deliberately does not (per-worker identity, chief-side
   only, security transport, explicit-install chaos knobs). A knob in
   neither set is a finding: an operator exporting it on the chief
   would silently configure only the chief.

Writes (``os.environ[k] = v``, ``.setdefault``, ``.pop``, ``del``,
``monkeypatch.setenv``) are not reads and are ignored.
"""
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Scanned roots, relative to the repo.
SCAN_ROOTS = ('autodist_tpu', 'tools', 'tests', 'examples', 'bench.py',
              '__graft_entry__.py')

#: Undeclared raw reads allowed, with the reason. Empty on HEAD: every
#: knob the tree reads is registered. Add entries only for names that
#: deliberately must not enter the registry (none known today).
ALLOWED_RAW_READS = {}

#: ENV members that deliberately do NOT ride ``_FORWARDED_FLAGS``,
#: with the reason. Everything else must be forwarded.
FORWARD_EXEMPT = {
    'AUTODIST_WORKER':
        'per-worker identity, set explicitly by Coordinator._worker_env',
    'AUTODIST_STRATEGY_ID':
        'per-launch value, set explicitly by Coordinator._worker_env',
    'AUTODIST_PROCESS_ID':
        'per-worker identity, set explicitly by Coordinator._worker_env',
    'AUTODIST_NUM_PROCESSES':
        'per-launch value, set explicitly by Coordinator._worker_env',
    'AUTODIST_COORDINATOR_ADDR':
        'per-launch value, set explicitly by Coordinator._worker_env',
    'AUTODIST_RUN_ID':
        'per-launch nonce, issued and set explicitly by the launcher',
    'AUTODIST_DEBUG_REMOTE':
        'chief-side launcher behavior (print instead of ssh)',
    'AUTODIST_DUMP_GRAPHS':
        'per-process debug dumps; divergence is harmless',
    'AUTODIST_COORD_TOKEN':
        'deliberately not forwarded: env assignments ride the remote '
        'ssh command line (world-readable in ps); the secret ships as '
        'a mode-0600 file via AUTODIST_COORD_TOKEN_FILE instead',
    'AUTODIST_COORD_TOKEN_FILE':
        'set explicitly per worker after the token file is copied',
    'AUTODIST_ELASTIC_JOIN':
        'set per joiner by Coordinator.scale_up, never on the launch '
        'cohort',
    'AUTODIST_AUTO_CHECKPOINT_EVERY':
        'chief-side checkpoint backstop; workers never act on it',
    'AUTODIST_EXECUTE_REPLAN':
        'chief-side migration opt-in (cohort-wide propagation is '
        'ROADMAP 3a)',
    'AUTODIST_FAULT_PLAN':
        'chaos-only: honored only where a FaultLine is explicitly '
        'installed; production sessions never read it',
    'AUTODIST_STRAGGLER_POLICY':
        'chief-side monitor verdict policy: workers only emit spans '
        '(AUTODIST_TELEMETRY is forwarded) and never act on verdicts',
    'AUTODIST_MONITOR_WINDOW':
        'chief-side monitor statistics window; no worker reads it',
    'AUTODIST_RECALIBRATE_EVERY':
        "chief-side recalibration cadence; the refit constants feed "
        "only the chief's re-rank",
}

_PY_READ = re.compile(
    r'''os\.environ\.get\(\s*['"](AUTODIST_\w+)['"]'''
    r'''|os\.getenv\(\s*['"](AUTODIST_\w+)['"]'''
    r'''|(?<!del )os\.environ\[['"](AUTODIST_\w+)['"]\](?![ \t]*=[^=])''')
_CC_READ = re.compile(r'getenv\("(AUTODIST_\w+)"\)')


def _iter_sources():
    for root in SCAN_ROOTS:
        path = os.path.join(REPO, root)
        if os.path.isfile(path):
            yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames
                           if d not in ('__pycache__', '.git')]
            for fn in filenames:
                if fn.endswith(('.py', '.cc', '.h')):
                    yield os.path.join(dirpath, fn)


def raw_reads(files=None):
    """``[(relpath, lineno, name)]`` for every AUTODIST_* env read.

    Scans whole-file text (not per-line) so a call wrapped across lines
    for the 72-column style — ``os.environ.get(\\n    'AUTODIST_X')`` —
    still matches."""
    out = []
    own = os.path.abspath(__file__)
    for path in (files if files is not None else _iter_sources()):
        if os.path.abspath(path) == own:
            continue   # this module's own regex literals are not reads
        pat = _CC_READ if path.endswith(('.cc', '.h')) else _PY_READ
        with open(path, encoding='utf-8', errors='replace') as f:
            text = f.read()
        for m in pat.finditer(text):
            name = next(g for g in m.groups() if g)
            out.append((os.path.relpath(path, REPO),
                        text.count('\n', 0, m.start()) + 1, name))
    return out


def declared_env():
    from autodist_tpu.const import ENV
    return {e.name for e in ENV}


def forwarded_env():
    from autodist_tpu.runtime.coordinator import _FORWARDED_FLAGS
    return {e.name for e in _FORWARDED_FLAGS}


def analyze(files=None):
    """Run both invariants. Returns finding strings (empty = clean)."""
    findings = []
    declared = declared_env()
    for relpath, lineno, name in raw_reads(files):
        if name in declared:
            continue
        if name in ALLOWED_RAW_READS:
            continue
        findings.append(
            '%s:%d: reads undeclared env knob %s — register it in '
            "const.py's ENV (typed, validated, forwardable) or "
            'allowlist it in analysis/env_lint.py with a reason'
            % (relpath, lineno, name))
    for name in sorted(set(ALLOWED_RAW_READS) & declared):
        findings.append(
            'env_lint.ALLOWED_RAW_READS lists %s, which IS declared in '
            "const.py's ENV — stale allowlist entry" % name)
    forwarded = forwarded_env()
    for name in sorted(declared):
        if not name.startswith('AUTODIST_'):
            continue    # SYS_* reference-parity paths judged by hand
        in_fwd = name in forwarded
        in_exempt = name in FORWARD_EXEMPT
        if in_fwd and in_exempt:
            findings.append(
                'env knob %s is BOTH in coordinator._FORWARDED_FLAGS '
                'and env_lint.FORWARD_EXEMPT — resolve the conflict'
                % name)
        elif not in_fwd and not in_exempt:
            findings.append(
                'env knob %s is declared but neither forwarded '
                '(coordinator._FORWARDED_FLAGS) nor exempted with a '
                'reason (env_lint.FORWARD_EXEMPT): an operator '
                'exporting it on the chief silently configures only '
                'the chief' % name)
    for name in sorted(set(FORWARD_EXEMPT) - declared):
        findings.append(
            'env_lint.FORWARD_EXEMPT lists %s, which is not an ENV '
            'member — stale exemption' % name)
    return findings
