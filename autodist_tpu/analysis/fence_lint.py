"""Fence-coverage lint over the native coord-service dispatcher.

Statically parses ``native/coord_service.cc`` and proves, per
dispatched command, the writer-fencing contract the elastic-recovery
protocol rests on (PR 4): every MUTATING command must check
``is_fenced``/``is_fenced_locked`` and have an ``ERR fenced``
(``kFencedErr``) reply path, and every tensor-mutating ``B*`` command
must ALSO re-check under the tensor lock
(``reject_fenced_under_tensor_lock``) so one in-flight zombie frame
cannot commit after its fence bump.

The classification table below is the lint's ground truth: a command
the dispatcher matches that appears in NEITHER table is a finding —
adding a protocol command forces an explicit fencing decision here
(and a model-checker look; see ``docs/design/static-analysis.md``).

Absorbs ``tools/check_protocol.py``: the header comment's command
table must match the dispatcher's ``cmd == "..."`` set, and the header
paragraph enumerating the fenced mutating commands must match the
MUTATING table (BSTAT and BSADD have each drifted out of the header
before).
"""
import os
import re

SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    'autodist_tpu', 'native', 'coord_service.cc')

#: Commands that mutate durable state: each must be fence-checked with
#: an ERR fenced path. Values are the rationale (documentation the
#: lint enforces reading when the table changes).
MUTATING = {
    'SET': 'writes kv state',
    'DEL': 'erases a key/counter — a zombie delete corrupts state as '
           'surely as a write',
    'DELNS': 'purges a whole namespace',
    'INCR': 'advances counters (step publishes, claims, epochs); '
            'delta-0 reads are exempt inside the handler',
    'BSET': 'overwrites tensor data',
    'BADD': 'accumulates into tensor data',
    'BSADD': 'row-sparse scatter-add into tensor data',
    'BSTEP': 'applies an optimizer update to PS-resident state',
}

#: Tensor-mutating commands additionally re-check the fence under the
#: tensor lock: the global-mu check alone leaves a window where a
#: zombie frame already past it commits after the fence bump.
TENSOR_MUTATING = ('BSET', 'BADD', 'BSADD', 'BSTEP')

#: Commands allowed to skip the fence check, with the reason. Reads
#: and waits never fence (a zombie observing the world is harmless).
ALLOWED_UNFENCED = {
    'GET': 'read',
    'BGET': 'read (torn-read version contract)',
    'BSTAT': 'read (tensor introspection)',
    'BGETROWS': 'read (row fetch)',
    'WAITGE': 'wait (no mutation)',
    'MINWAIT': 'wait (no mutation)',
    'PING': 'liveness probe',
    'FENCE': 'binds the generation itself (rejects superseded binds)',
    'BARRIER': 'transient rendezvous arrivals only — withdrawn on '
               'timeout, never durable state; completing a round '
               'still needs k-1 live parties',
    'SHUTDOWN': 'operator action (sets the shutting_down flag only)',
}

#: AUTH is consumed by the connection handshake (serve_conn) before any
#: command reaches handle(); it belongs in the header but can never
#: appear in the dispatcher.
HANDSHAKE_ONLY = {'AUTH'}


def _read(text=None):
    if text is None:
        with open(SRC) as f:
            text = f.read()
    return text


def documented_commands(text):
    """Commands listed in the header comment's protocol table: lines of
    the form ``//   CMD <args...> -> reply`` before the first
    ``#include``."""
    header = text.split('#include', 1)[0]
    return set(re.findall(r'^//   ([A-Z][A-Z0-9]*)\b', header, re.M))


def header_fenced_commands(text):
    """The mutating-command enumeration in the header's writer-fencing
    paragraph ('every mutating command on the connection — X, Y — is
    rejected ...')."""
    header = text.split('#include', 1)[0]
    m = re.search(r'every mutating command[^—]*—([^—]+)—', header,
                  re.S)
    if not m:
        return None
    return set(re.findall(r'\b([A-Z][A-Z0-9]*)\b', m.group(1)))


def _handle_body(text):
    """The body of the ``handle()`` function (the dispatcher) — scoped
    so ``payload_size``'s own ``cmd ==`` matches don't alias."""
    m = re.search(r'std::string handle\(', text)
    if not m:
        return None
    i = text.index('{', m.end())
    depth = 0
    for j in range(i, len(text)):
        if text[j] == '{':
            depth += 1
        elif text[j] == '}':
            depth -= 1
            if depth == 0:
                return text[i:j + 1]
    return None


def dispatched_blocks(text):
    """``{command: block source}`` for every ``if (cmd == "X")`` in the
    dispatcher — the braced block, or the single statement for
    brace-less arms (PING)."""
    body = _handle_body(text)
    if body is None:
        return {}
    blocks = {}
    for m in re.finditer(r'if \(cmd == "([A-Z][A-Z0-9]*)"\)', body):
        cmd = m.group(1)
        k = m.end()
        while k < len(body) and body[k] in ' \n':
            k += 1
        if k < len(body) and body[k] == '{':
            depth = 0
            for j in range(k, len(body)):
                if body[j] == '{':
                    depth += 1
                elif body[j] == '}':
                    depth -= 1
                    if depth == 0:
                        blocks[cmd] = body[k:j + 1]
                        break
        else:
            blocks[cmd] = body[k:body.index(';', k) + 1]
    return blocks


def dispatched_commands(text):
    """Commands the dispatcher actually matches."""
    return set(dispatched_blocks(text))


def find_drift(text=None):
    """The absorbed ``check_protocol`` check: header command table vs
    dispatcher. Returns human-readable problems (empty = in sync)."""
    text = _read(text)
    doc = documented_commands(text)
    disp = dispatched_commands(text)
    problems = []
    for cmd in sorted(disp - doc):
        problems.append('dispatched but not documented in the header '
                        'comment: %s' % cmd)
    for cmd in sorted(doc - disp - HANDSHAKE_ONLY):
        problems.append('documented in the header comment but not '
                        'dispatched: %s' % cmd)
    if not doc:
        problems.append('no documented commands found — the header '
                        'comment table moved or changed format')
    return problems


def analyze(text=None):
    """Full fence-coverage lint. Returns finding strings (empty =
    clean)."""
    text = _read(text)
    findings = ['coord_service.cc: ' + p for p in find_drift(text)]
    blocks = dispatched_blocks(text)
    if not blocks:
        return findings + ['coord_service.cc: could not locate the '
                           'handle() dispatcher — the lint must be '
                           'updated with the new layout']
    classified = set(MUTATING) | set(ALLOWED_UNFENCED)
    for cmd in sorted(set(blocks) - classified):
        findings.append(
            'coord_service.cc: dispatched command %s is not classified '
            'in analysis/fence_lint.py (MUTATING or ALLOWED_UNFENCED) '
            '— a new protocol command needs an explicit fencing '
            'decision' % cmd)
    for cmd in sorted(classified - set(blocks)):
        findings.append(
            'coord_service.cc: %s is classified in '
            'analysis/fence_lint.py but no longer dispatched — stale '
            'table entry' % cmd)
    for cmd in sorted(set(MUTATING) & set(blocks)):
        block = blocks[cmd]
        if 'is_fenced_locked(' not in block and \
                'is_fenced(' not in block:
            findings.append(
                'coord_service.cc: mutating command %s (%s) has no '
                'fence check (is_fenced/is_fenced_locked)'
                % (cmd, MUTATING[cmd]))
        if 'kFencedErr' not in block:
            findings.append(
                'coord_service.cc: mutating command %s has no ERR '
                'fenced reply path (kFencedErr)' % cmd)
        if cmd in TENSOR_MUTATING and \
                'reject_fenced_under_tensor_lock(' not in block:
            findings.append(
                'coord_service.cc: tensor-mutating command %s does not '
                're-check the fence under the tensor lock '
                '(reject_fenced_under_tensor_lock) — one in-flight '
                'zombie frame could commit after its fence bump' % cmd)
    hdr = header_fenced_commands(text)
    if hdr is None:
        findings.append(
            'coord_service.cc: the header\'s writer-fencing paragraph '
            '("every mutating command ... — X, Y — is rejected") was '
            'not found — keep the enumeration, the lint pins it to '
            'the MUTATING table')
    else:
        for cmd in sorted(set(MUTATING) - hdr):
            findings.append(
                'coord_service.cc: header writer-fencing paragraph '
                'does not list mutating command %s' % cmd)
        for cmd in sorted(hdr - set(MUTATING)):
            findings.append(
                'coord_service.cc: header writer-fencing paragraph '
                'lists %s, which the lint does not classify as '
                'mutating' % cmd)
    return findings
