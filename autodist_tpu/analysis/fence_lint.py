"""Fence-coverage + payload-bound lint over the native coord-service
dispatcher.

Statically parses ``native/coord_service.cc`` and proves, per
dispatched command, the writer-fencing contract the elastic-recovery
protocol rests on (PR 4): every MUTATING command must check
``is_fenced``/``is_fenced_locked`` and have an ``ERR fenced``
(``kFencedErr``) reply path, and every tensor-mutating ``B*`` command
must ALSO re-check under the tensor lock
(``reject_fenced_under_tensor_lock``) so one in-flight zombie frame
cannot commit after its fence bump.

It also generalizes the PR 5 BGETROWS hardening into a rule: every
command whose header DECLARES a size (payload bytes to buffer, or
reply dimensions to allocate) must bound that declaration against
``kMaxPayload`` BEFORE any buffer is sized from it — an unvalidated
product can ``bad_alloc`` (or wrap ``size_t``) and kill the whole
control plane. Request-side declarations are bounded in
``payload_size()`` (returning ``kBadPayload``); reply-side
declarations are bounded inside the command's own dispatcher block.
The :data:`PAYLOAD_BOUNDED` table is the ground truth; a dispatcher
block that touches the request ``payload`` without a table entry is a
finding, so a new payload-bearing command forces an explicit bounding
decision.

The classification tables below are the lint's ground truth: a
command the dispatcher matches that appears in NO table is a finding —
adding a protocol command forces an explicit fencing decision here
(and a model-checker look; see ``docs/design/static-analysis.md``).

Absorbs ``tools/check_protocol.py``: the header comment's command
table must match the dispatcher's ``cmd == "..."`` set, and the header
paragraph enumerating the fenced mutating commands must match the
MUTATING table (BSTAT and BSADD have each drifted out of the header
before).
"""
import os
import re

SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    'autodist_tpu', 'native', 'coord_service.cc')

#: Commands that mutate durable state: each must be fence-checked with
#: an ERR fenced path. Values are the rationale (documentation the
#: lint enforces reading when the table changes).
MUTATING = {
    'SET': 'writes kv state',
    'DEL': 'erases a key/counter — a zombie delete corrupts state as '
           'surely as a write',
    'DELNS': 'purges a whole namespace',
    'INCR': 'advances counters (step publishes, claims, epochs); '
            'delta-0 reads are exempt inside the handler',
    'BSET': 'overwrites tensor data',
    'BADD': 'accumulates into tensor data',
    'BSADD': 'row-sparse scatter-add into tensor data',
    'BSTEP': 'applies an optimizer update to PS-resident state',
}

#: Tensor-mutating commands additionally re-check the fence under the
#: tensor lock: the global-mu check alone leaves a window where a
#: zombie frame already past it commits after the fence bump.
TENSOR_MUTATING = ('BSET', 'BADD', 'BSADD', 'BSTEP')

#: Commands allowed to skip the fence check, with the reason. Reads
#: and waits never fence (a zombie observing the world is harmless).
ALLOWED_UNFENCED = {
    'GET': 'read',
    'BGET': 'read (torn-read version contract)',
    'BSTAT': 'read (tensor introspection)',
    'BGETROWS': 'read (row fetch)',
    'WAITGE': 'wait (no mutation)',
    'MINWAIT': 'wait (no mutation)',
    'PING': 'liveness probe',
    'FENCE': 'binds the generation itself (rejects superseded binds)',
    'BARRIER': 'transient rendezvous arrivals only — withdrawn on '
               'timeout, never durable state; completing a round '
               'still needs k-1 live parties',
    'SHUTDOWN': 'operator action (sets the shutting_down flag only)',
}

#: AUTH is consumed by the connection handshake (serve_conn) before any
#: command reaches handle(); it belongs in the header but can never
#: appear in the dispatcher.
HANDSHAKE_ONLY = {'AUTH'}

#: The epoch-swap handshake's key schema (runtime/swap_keys.py) and the
#: protocol verbs each key rides, with the fencing rationale. The swap
#: handshake introduces NO new protocol commands — every write rides a
#: verb the MUTATING table already fences, which is exactly the
#: property :func:`check_swap_keys` proves: a zombie chief (superseded
#: fence generation) cannot stage, cancel, or arm a swap, because SET/
#: INCR/DELNS all reject it. Key templates use ``<g>`` for the staged
#: generation and ``<w>`` for a worker ordinal.
SWAP_KEY_VERBS = {
    'swap/gen': 'INCR — monotone generation counter; the stage bump '
                'is fenced, discovery reads are delta-0',
    'swap/<g>/plan': 'SET/GET/DELNS — staged plan payload; staging '
                     'and cancel are fenced writes',
    'swap/<g>/ack/<w>': 'SET/GET/DELNS — peer validation ack '
                        '(fenced: a zombie peer cannot fill a quorum)',
    'swap/<g>/nack/<w>': 'SET/GET/DELNS — peer rejection + reason '
                         '(fenced: a zombie cannot cancel a live '
                         'swap)',
    'swap/<g>/B': 'SET/GET/DELNS — the armed commit boundary; arming '
                  'is a fenced write',
    'swap/<g>/ready': 'SET/GET/DELNS — chief finished re-keying the '
                      'authoritative PS copies (GET via wait_key '
                      'polling)',
}

#: DELNS prefixes in swap_keys.py — namespace sweeps, not keys; they
#: cover whole generations (cancel / previous-generation purge) or the
#: whole subtree (run-end purge).
SWAP_KEY_PREFIXES = {'swap/', 'swap/<g>/'}

#: coord_client methods the swap-key module may call, mapped to the
#: protocol verb each one speaks (wait_key is a GET poll loop).
_SWAP_CLIENT_VERBS = {
    'set': 'SET',
    'get': 'GET',
    'incr': 'INCR',
    'delete_namespace': 'DELNS',
    'wait_key': 'GET',
}

#: Commands whose header line declares a size. 'request' = the
#: declared payload bytes are buffered before handle() runs, so the
#: bound must live in ``payload_size()`` (return ``kBadPayload`` past
#: ``kMaxPayload``); 'reply' = the block allocates a reply buffer from
#: declared dimensions, so the bound must live in the block itself
#: (the PR 5 BGETROWS fix: a 256 GB nrows*ncols declaration must be
#: refused before the allocation, not discovered as bad_alloc).
PAYLOAD_BOUNDED = {
    'BSET': ('request',),
    'BADD': ('request',),
    'BSTEP': ('request',),
    'BSADD': ('request',),
    'BGETROWS': ('request', 'reply'),
}


def _read(text=None):
    if text is None:
        with open(SRC) as f:
            text = f.read()
    return text


def documented_commands(text):
    """Commands listed in the header comment's protocol table: lines of
    the form ``//   CMD <args...> -> reply`` before the first
    ``#include``."""
    header = text.split('#include', 1)[0]
    return set(re.findall(r'^//   ([A-Z][A-Z0-9]*)\b', header, re.M))


def header_fenced_commands(text):
    """The mutating-command enumeration in the header's writer-fencing
    paragraph ('every mutating command on the connection — X, Y — is
    rejected ...')."""
    header = text.split('#include', 1)[0]
    m = re.search(r'every mutating command[^—]*—([^—]+)—', header,
                  re.S)
    if not m:
        return None
    return set(re.findall(r'\b([A-Z][A-Z0-9]*)\b', m.group(1)))


def _fn_body(text, pattern):
    """The balanced-brace body of the first function whose signature
    matches ``pattern``, or None."""
    m = re.search(pattern, text)
    if not m:
        return None
    i = text.index('{', m.end())
    depth = 0
    for j in range(i, len(text)):
        if text[j] == '{':
            depth += 1
        elif text[j] == '}':
            depth -= 1
            if depth == 0:
                return text[i:j + 1]
    return None


def _handle_body(text):
    """The body of the ``handle()`` function (the dispatcher) — scoped
    so ``payload_size``'s own ``cmd ==`` matches don't alias."""
    return _fn_body(text, r'std::string handle\(')


def payload_size_branches(text):
    """``{command: branch source}`` inside ``payload_size()`` — the
    function that decides how many request-payload bytes to buffer
    from a header declaration. A branch runs from the first line
    mentioning the command to the next command's first line (commands
    sharing one guard line — the BSET/BADD/BSTEP tail — share the
    remainder). None when the function is missing."""
    body = _fn_body(text, r'size_t payload_size\(')
    if body is None:
        return None
    by_line = {}
    for m in re.finditer(r'cmd [=!]= "([A-Z][A-Z0-9]*)"', body):
        ls = body.rfind('\n', 0, m.start()) + 1
        by_line.setdefault(ls, []).append(m.group(1))
    first = {}
    for ls in sorted(by_line):
        for cmd in by_line[ls]:
            first.setdefault(cmd, ls)
    starts = sorted(set(first.values()))
    out = {}
    for cmd, ls in first.items():
        nxt = [s for s in starts if s > ls]
        out[cmd] = body[ls:nxt[0] if nxt else len(body)]
    return out


def dispatched_blocks(text):
    """``{command: block source}`` for every ``if (cmd == "X")`` in the
    dispatcher — the braced block, or the single statement for
    brace-less arms (PING)."""
    body = _handle_body(text)
    if body is None:
        return {}
    blocks = {}
    for m in re.finditer(r'if \(cmd == "([A-Z][A-Z0-9]*)"\)', body):
        cmd = m.group(1)
        k = m.end()
        while k < len(body) and body[k] in ' \n':
            k += 1
        if k < len(body) and body[k] == '{':
            depth = 0
            for j in range(k, len(body)):
                if body[j] == '{':
                    depth += 1
                elif body[j] == '}':
                    depth -= 1
                    if depth == 0:
                        blocks[cmd] = body[k:j + 1]
                        break
        else:
            blocks[cmd] = body[k:body.index(';', k) + 1]
    return blocks


def dispatched_commands(text):
    """Commands the dispatcher actually matches."""
    return set(dispatched_blocks(text))


def find_drift(text=None):
    """The absorbed ``check_protocol`` check: header command table vs
    dispatcher. Returns human-readable problems (empty = in sync)."""
    text = _read(text)
    doc = documented_commands(text)
    disp = dispatched_commands(text)
    problems = []
    for cmd in sorted(disp - doc):
        problems.append('dispatched but not documented in the header '
                        'comment: %s' % cmd)
    for cmd in sorted(doc - disp - HANDSHAKE_ONLY):
        problems.append('documented in the header comment but not '
                        'dispatched: %s' % cmd)
    if not doc:
        problems.append('no documented commands found — the header '
                        'comment table moved or changed format')
    return problems


def _strip_comments(src):
    """Drop ``//`` line and ``/* */`` block comments: a bound that
    exists only in prose must not satisfy the lint."""
    src = re.sub(r'/\*.*?\*/', '', src, flags=re.S)
    return re.sub(r'//[^\n]*', '', src)


def check_payload_bounds(text, blocks=None):
    """The generalized PR 5 hardening: every size-declaring command's
    declared allocation is bounded against ``kMaxPayload`` before any
    buffer is sized from it, and every dispatcher block that touches
    the request ``payload`` carries a :data:`PAYLOAD_BOUNDED` entry.
    Returns finding strings (empty = clean)."""
    if blocks is None:
        blocks = dispatched_blocks(text)
    findings = []
    branches = payload_size_branches(text)
    for cmd in sorted(set(PAYLOAD_BOUNDED) - set(blocks)):
        findings.append(
            'coord_service.cc: %s is classified in '
            'analysis/fence_lint.py PAYLOAD_BOUNDED but no longer '
            'dispatched — stale table entry' % cmd)
    for cmd in sorted(set(PAYLOAD_BOUNDED) & set(blocks)):
        roles = PAYLOAD_BOUNDED[cmd]
        if 'request' in roles:
            if branches is None or cmd not in branches:
                findings.append(
                    'coord_service.cc: %s declares a request payload '
                    'size but payload_size() never sizes it — the '
                    'declared bytes are buffered unbounded' % cmd)
            else:
                seg = _strip_comments(branches[cmd])
                if 'kMaxPayload' not in seg or 'kBadPayload' not in seg:
                    findings.append(
                        'coord_service.cc: %s\'s request-size '
                        'declaration is not bounded against '
                        'kMaxPayload (with a kBadPayload refusal) in '
                        'payload_size() before the bytes are buffered '
                        '— an unvalidated declaration can bad_alloc/'
                        'wrap and kill the whole control plane' % cmd)
        if 'reply' in roles and \
                'kMaxPayload' not in _strip_comments(blocks[cmd]):
            findings.append(
                'coord_service.cc: %s allocates a reply from declared '
                'dimensions without bounding them against kMaxPayload '
                'inside its dispatcher block (the PR 5 BGETROWS '
                'hardening: refuse the declaration, don\'t discover '
                'it as bad_alloc)' % cmd)
    for cmd in sorted(set(blocks) - set(PAYLOAD_BOUNDED)):
        if re.search(r'\bpayload\b', _strip_comments(blocks[cmd])):
            findings.append(
                'coord_service.cc: dispatched command %s touches the '
                'request payload but is not classified in '
                'analysis/fence_lint.py PAYLOAD_BOUNDED — a new '
                'payload-bearing command needs an explicit '
                'size-bounding decision' % cmd)
    return findings


def check_read_only_client(mutating=None):
    """The read-only-client invariant, machine-checked (ISSUE 17): the
    serving tier's reader connections refuse every verb this lint
    classifies as MUTATING, plus FENCE (not a write, but it BINDS a
    writer generation — a reader holding one would enter the cohort's
    zombie-detection protocol). The guard lives in coord_client's
    ``READ_ONLY_BLOCKED``; if a new mutating command lands in the
    service without a matching entry there, the reader guard silently
    stops covering the write surface — this check turns that drift
    into a finding instead of folklore. Returns finding strings."""
    from autodist_tpu.runtime import coord_client
    mutating = set(MUTATING if mutating is None else mutating)
    blocked = set(coord_client.READ_ONLY_BLOCKED)
    findings = []
    for cmd in sorted(mutating - blocked):
        findings.append(
            'coord_client.py: mutating command %s (%s) is missing from '
            'READ_ONLY_BLOCKED — a read-only serving connection could '
            'mutate the training namespace' % (cmd, MUTATING.get(
                cmd, 'classified mutating by fence_lint')))
    if 'FENCE' not in blocked:
        findings.append(
            'coord_client.py: FENCE is missing from READ_ONLY_BLOCKED '
            '— a read-only connection could bind a writer generation, '
            'and readers must never take writer fences')
    for cmd in sorted(blocked - mutating - {'FENCE'}):
        findings.append(
            'coord_client.py: READ_ONLY_BLOCKED lists %s, which '
            'fence_lint does not classify as mutating (and is not '
            'FENCE) — stale entry, or a new mutating command missing '
            'from the MUTATING table' % cmd)
    return findings


def _swap_keys_source():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), 'runtime', 'swap_keys.py')
    with open(path) as f:
        return f.read()


def _normalize_swap_template(lit):
    """A ``swap/...`` string literal from swap_keys.py in the table's
    ``<g>``/``<w>`` template form: the first ``%d`` is the generation,
    a second is a worker ordinal."""
    out = lit.replace('%d', '<g>', 1)
    return out.replace('%d', '<w>', 1)


def check_swap_keys(src=None):
    """The epoch-swap key-schema classification (PR 19): statically
    parse ``runtime/swap_keys.py`` and prove (a) every coordinator
    verb it speaks is classified (MUTATING or ALLOWED_UNFENCED — its
    writes all ride fenced verbs, so a zombie chief cannot stage,
    cancel, or arm a swap), and (b) every ``swap/*`` key template it
    builds has a :data:`SWAP_KEY_VERBS` entry (and vice versa) — a new
    swap key or verb forces an explicit fencing decision here instead
    of drifting in silently. Returns finding strings."""
    import ast
    src = _swap_keys_source() if src is None else src
    tree = ast.parse(src)
    findings = []
    methods = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == 'client':
            methods.add(node.func.attr)
    for m in sorted(methods - set(_SWAP_CLIENT_VERBS)):
        findings.append(
            'swap_keys.py: calls coord-client method %s, which '
            'fence_lint does not map to a protocol verb '
            '(_SWAP_CLIENT_VERBS) — a new verb in the swap handshake '
            'needs an explicit fencing decision' % m)
    for m in sorted(methods & set(_SWAP_CLIENT_VERBS)):
        verb = _SWAP_CLIENT_VERBS[m]
        if verb not in MUTATING and verb not in ALLOWED_UNFENCED:
            findings.append(
                'swap_keys.py: speaks verb %s (via client.%s) which '
                'is classified in neither MUTATING nor '
                'ALLOWED_UNFENCED — the swap handshake must ride '
                'classified verbs only' % (verb, m))
    # the values of swap_keys.MODEL_SYMBOLS are ABSTRACT model-side
    # symbols (epoch_swap_model vocabulary), not coordinator keys —
    # collect them so the literal sweep below skips them
    abstract = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == 'MODEL_SYMBOLS'
                for t in node.targets) and \
                isinstance(node.value, ast.Dict):
            for v in node.value.values:
                if isinstance(v, ast.Constant) and \
                        isinstance(v.value, str):
                    abstract.add(v.value)
    lits = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                node.value.startswith('swap/') and \
                node.value not in abstract:
            lits.add(_normalize_swap_template(node.value))
    keys = lits - SWAP_KEY_PREFIXES
    for k in sorted(keys - set(SWAP_KEY_VERBS)):
        findings.append(
            'swap_keys.py: builds swap key %s with no '
            'SWAP_KEY_VERBS classification in analysis/fence_lint.py '
            '— a new swap/<gen> key needs an explicit fencing '
            'decision' % k)
    for k in sorted(set(SWAP_KEY_VERBS) - keys):
        findings.append(
            'fence_lint.py: SWAP_KEY_VERBS classifies %s, which '
            'swap_keys.py no longer builds — stale table entry' % k)
    for p in sorted(SWAP_KEY_PREFIXES - lits):
        findings.append(
            'fence_lint.py: SWAP_KEY_PREFIXES lists %s, which '
            'swap_keys.py no longer uses — stale prefix entry' % p)
    return findings


def analyze(text=None):
    """Full fence-coverage lint. Returns finding strings (empty =
    clean)."""
    text = _read(text)
    findings = ['coord_service.cc: ' + p for p in find_drift(text)]
    findings.extend(check_read_only_client())
    findings.extend(check_swap_keys())
    blocks = dispatched_blocks(text)
    if not blocks:
        return findings + ['coord_service.cc: could not locate the '
                           'handle() dispatcher — the lint must be '
                           'updated with the new layout']
    classified = set(MUTATING) | set(ALLOWED_UNFENCED)
    for cmd in sorted(set(blocks) - classified):
        findings.append(
            'coord_service.cc: dispatched command %s is not classified '
            'in analysis/fence_lint.py (MUTATING or ALLOWED_UNFENCED) '
            '— a new protocol command needs an explicit fencing '
            'decision' % cmd)
    for cmd in sorted(classified - set(blocks)):
        findings.append(
            'coord_service.cc: %s is classified in '
            'analysis/fence_lint.py but no longer dispatched — stale '
            'table entry' % cmd)
    for cmd in sorted(set(MUTATING) & set(blocks)):
        block = blocks[cmd]
        if 'is_fenced_locked(' not in block and \
                'is_fenced(' not in block:
            findings.append(
                'coord_service.cc: mutating command %s (%s) has no '
                'fence check (is_fenced/is_fenced_locked)'
                % (cmd, MUTATING[cmd]))
        if 'kFencedErr' not in block:
            findings.append(
                'coord_service.cc: mutating command %s has no ERR '
                'fenced reply path (kFencedErr)' % cmd)
        if cmd in TENSOR_MUTATING and \
                'reject_fenced_under_tensor_lock(' not in block:
            findings.append(
                'coord_service.cc: tensor-mutating command %s does not '
                're-check the fence under the tensor lock '
                '(reject_fenced_under_tensor_lock) — one in-flight '
                'zombie frame could commit after its fence bump' % cmd)
    findings.extend(check_payload_bounds(text, blocks))
    hdr = header_fenced_commands(text)
    if hdr is None:
        findings.append(
            'coord_service.cc: the header\'s writer-fencing paragraph '
            '("every mutating command ... — X, Y — is rejected") was '
            'not found — keep the enumeration, the lint pins it to '
            'the MUTATING table')
    else:
        for cmd in sorted(set(MUTATING) - hdr):
            findings.append(
                'coord_service.cc: header writer-fencing paragraph '
                'does not list mutating command %s' % cmd)
        for cmd in sorted(hdr - set(MUTATING)):
            findings.append(
                'coord_service.cc: header writer-fencing paragraph '
                'lists %s, which the lint does not classify as '
                'mutating' % cmd)
    return findings
