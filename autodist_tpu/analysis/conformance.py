"""Post-hoc trace conformance: replay a flight-recorder dump through
the protocol model's invariants.

The PR 9 model checker (:mod:`~autodist_tpu.analysis.explore` over
:mod:`~autodist_tpu.analysis.protocol_model`) proves the ABSTRACT
protocol's orderings safe; this module closes the loop with the LIVE
system: the telemetry plane's crash flight recorder
(:mod:`autodist_tpu.telemetry.flight`) captures the control-plane
events a real run actually performed — fence binds, epoch bumps, step
publishes, exclusions, admit phases, replan stage/swap — and this
checker replays that recorded sequence against the same invariants the
model checker enumerates interleavings over:

- **no released-counter resurrection** (``resurrection``) — once a
  worker's step counter is released (exclusion / cap-retire / clean
  close sentinel), no later publish may land it below the sentinel;
  replayed through :func:`protocol_model._check_resurrection` itself.
- **no fenced write commits** (``fenced-write-commit``) — a recorded
  event IS a committed mutation (the session records after the RPC
  returns OK), so a step publish recorded for a worker whose exclusion
  claim precedes it in the trace means a zombie write landed.
- **fence-before-claim** (``unfenced-exclude``) — an exclusion claim
  recorded with no prior fence bump for the same worker is the
  ``UNFENCED_EXCLUDE`` ordering the model counterexamples.
- **no invisible frozen counter** (``admit-inversion``) — an admit's
  step-floor publish recorded BEFORE its membership epoch bump is the
  ``PR6_ADMIT_INVERSION`` ordering: a joiner dying in that window
  leaves a frozen counter in the gate's prefix-min no survivor can
  exclude. Likewise every admit-path write must follow the admit's
  fence bind (``unfenced-admit-write``).
- **monotonicity** (``step-regression`` / ``epoch-regression``) — a
  worker's published steps and the membership epoch only move forward.
- **slowdown pairing** (``unmatched-recovery``) — the performance
  sentry's ``recovered`` event clears a prior ``slowdown`` verdict for
  the same worker; a recovery with no preceding slowdown in the trace
  is an inconsistent perf narrative. Absence-based like
  ``unfenced-exclude``: suppressed on truncated rings (the slowdown
  may simply have scrolled off the bound) and re-armed by a retained
  ``run_start``.

A conformant dump returns ``[]``; chaos tests assert real runs produce
conformant traces, and ``tools/analyze.py --conformance <dump>`` is
the operator CLI. What this deliberately does NOT do: re-explore
interleavings (the trace is ONE interleaving — the one that happened)
or validate tensor payloads/liveness (a dump is a bounded window, not
a complete history; events that scrolled off the ring are judged
absent, so ordering rules only fire when BOTH halves are present).
"""
from autodist_tpu.analysis import protocol_model as pm


def _fmt(ev, kind, msg):
    who = ev.get('worker', ev.get('by', '?'))
    return ('trace conformance [%s] at event #%s (%s %s): %s'
            % (kind, ev.get('seq', '?'), ev.get('kind', '?'), who,
               msg))


def check_events(events):
    """Replay one recorded event sequence; returns finding strings
    (empty = the trace conforms to the protocol model)."""
    findings = []
    m = {'counters': {}, 'kv': {}, 'procs': {}, 'slot_owner': {},
         'violation': None}
    fenced = set()        # workers whose generation a fence bump hit
    excluded = {}         # worker -> seq of the exclusion claim
    admit_seen = {}       # worker -> set of admit kinds already seen
    last_step = {}        # worker -> last published step
    slowdown_open = {}    # worker -> seq of the active slowdown verdict
    last_epoch = 0
    # a ring whose first retained event is not seq 1 lost its oldest
    # events to the bound: absence-based rules (fence bump missing
    # before a claim) must not fire — the missing half may simply
    # have scrolled off
    truncated = bool(events) and events[0].get('seq', 1) > 1

    def model_violation(ev):
        if m['violation'] is not None:
            kind, msg = m['violation']
            findings.append(_fmt(ev, kind, msg))
            m['violation'] = None

    needs_worker = ('fence_bump', 'exclude_claim', 'release',
                    'admit_cap_retire', 'admit_claim',
                    'admit_fence_bind', 'admit_epoch_bump',
                    'admit_floor_publish', 'step_publish',
                    'slowdown', 'recovered')
    for ev in events:
        kind = ev.get('kind', '')
        w = ev.get('worker')
        if kind == 'run_start':
            # a new session in the same process: the ring is
            # process-wide, so per-run tracking resets here — run B's
            # step 1 after run A's step N is not a regression. The
            # boundary also ends any truncation: everything after a
            # RETAINED run_start is complete by construction, so
            # absence-based rules re-arm for this run.
            m = {'counters': {}, 'kv': {}, 'procs': {},
                 'slot_owner': {}, 'violation': None}
            fenced = set()
            excluded = {}
            admit_seen = {}
            last_step = {}
            slowdown_open = {}
            last_epoch = 0
            truncated = False
            continue
        if kind in needs_worker and not w:
            # a truncated/hand-edited dump is reported, never a crash
            findings.append(_fmt(
                ev, 'malformed-event',
                "event of kind %r carries no 'worker' field — the "
                'trace is truncated or was edited; ordering '
                'invariants cannot be attributed' % kind))
            continue
        if kind == 'slowdown':
            # the performance sentry opened a verdict; nothing to
            # judge beyond pairing — a slowdown is an observation, not
            # a mutation
            slowdown_open[w] = ev.get('seq')
            continue
        if kind == 'recovered':
            if w not in slowdown_open and not truncated:
                # absence-based, same rule as unfenced-exclude: only
                # judged on an untruncated ring (the opening slowdown
                # may have scrolled off the bound)
                findings.append(_fmt(
                    ev, 'unmatched-recovery',
                    'recovered recorded with no prior slowdown verdict '
                    'for %s — the perf narrative is inconsistent '
                    '(monitor transitions are strictly slowdown -> '
                    'recovered)' % w))
            slowdown_open.pop(w, None)
            continue
        if kind in ('fence_bump', 'admit_fence_bind', 'fence_bind'):
            if kind == 'fence_bump':
                fenced.add(w)
            else:
                admit_seen.setdefault(w, set()).add(kind)
            continue
        if kind == 'exclude_claim':
            if w not in fenced and not truncated:
                # absence-based: only judged on an untruncated ring
                # (a fence bump that scrolled off is not a violation)
                findings.append(_fmt(
                    ev, 'unfenced-exclude',
                    'exclusion claim recorded with no prior fence bump '
                    'for %s — the moment the claim is observable the '
                    "zombie's writes must already be rejected on every "
                    'service (protocol_model UNFENCED_EXCLUDE)' % w))
            excluded.setdefault(w, ev.get('seq'))
            m['counters']['excluded/' + w] = \
                m['counters'].get('excluded/' + w, 0) + 1
            continue
        if kind in ('release', 'admit_cap_retire'):
            m['kv']['released/' + (w or '')] = '1'
            m['counters']['step/' + (w or '')] = pm.SENTINEL
            continue
        if kind in ('epoch_bump', 'epoch_adopt', 'admit_epoch_bump'):
            epoch = ev.get('epoch', 0)
            if epoch < last_epoch:
                findings.append(_fmt(
                    ev, 'epoch-regression',
                    'membership epoch moved backwards (%d after %d) — '
                    'the epoch counter is monotone by construction'
                    % (epoch, last_epoch)))
            last_epoch = max(last_epoch, epoch)
            if kind == 'admit_epoch_bump':
                seen = admit_seen.setdefault(w, set())
                if 'admit_fence_bind' not in seen and \
                        'admit_claim' in seen:
                    findings.append(_fmt(
                        ev, 'unfenced-admit-write',
                        'admit epoch bump recorded before the fence '
                        'bind for %s — every admit-path write must '
                        'already be fenceable' % w))
                seen.add(kind)
            continue
        if kind == 'admit_claim':
            admit_seen.setdefault(w, set()).add(kind)
            continue
        if kind == 'admit_floor_publish':
            seen = admit_seen.setdefault(w, set())
            # anchored on the claim: with the claim in-window, the
            # whole admit tail is in-window too, so a missing epoch
            # bump before this publish is a real inversion, not ring
            # truncation
            if 'admit_epoch_bump' not in seen and \
                    ('admit_claim' in seen or not truncated):
                findings.append(_fmt(
                    ev, 'admit-inversion',
                    'adopted step floor published BEFORE the '
                    'membership epoch bump for %s — violates "no '
                    'invisible frozen counter": a joiner dying in this '
                    'window leaves a step counter inside the gate\'s '
                    'prefix-min that no survivor\'s membership view '
                    'contains, a permanent cohort stall '
                    '(protocol_model PR6_ADMIT_INVERSION)' % w))
            if 'admit_fence_bind' not in seen and 'admit_claim' in seen:
                findings.append(_fmt(
                    ev, 'unfenced-admit-write',
                    'admit floor publish recorded before the fence '
                    'bind for %s' % w))
            seen.add(kind)
            # the floor publish is a step publish; fall through to the
            # model's counter semantics below
            step = ev.get('floor', 0)
            m['counters']['step/' + w] = max(
                m['counters'].get('step/' + w, 0), step)
            pm._check_resurrection(m, 'step/' + w)
            model_violation(ev)
            last_step[w] = max(last_step.get(w, 0), step)
            continue
        if kind == 'step_publish':
            step = ev.get('step', 0)
            if w in excluded and step < pm.SENTINEL:
                findings.append(_fmt(
                    ev, 'fenced-write-commit',
                    'step publish for %s recorded AFTER its exclusion '
                    'claim (event #%s) — a recorded event is a '
                    'committed mutation, so a zombie write landed '
                    'past its fence (protocol_model '
                    'fenced-write-commit)' % (w, excluded[w])))
            if step < last_step.get(w, 0) and step < pm.SENTINEL:
                findings.append(_fmt(
                    ev, 'step-regression',
                    'published step moved backwards for %s (%d after '
                    '%d) — step counters are monotone under publishes'
                    % (w, step, last_step.get(w, 0))))
            # replay into the model's counter state so the RELEASED
            # check is literally protocol_model's: a recorded publish
            # is a committed mutation, so when the trace claims a
            # below-sentinel publish for a released worker, the model
            # state takes that value and the model's own invariant
            # (_check_resurrection) judges it
            cur = m['counters'].get('step/' + w, 0)
            if m['kv'].get('released/' + w) and step < pm.SENTINEL:
                m['counters']['step/' + w] = step
            else:
                m['counters']['step/' + w] = max(cur, step)
            pm._check_resurrection(m, 'step/' + w)
            model_violation(ev)
            last_step[w] = max(last_step.get(w, 0), step)
            continue
        # every other kind (launch/autoscale/replan/close/heartbeat
        # bookkeeping) carries no ordering invariant here
    return findings


def check_dump(path):
    """Load a flight-recorder dump and check it; returns
    ``(findings, meta)``. Delegates the ``swap_*`` event kinds to
    :mod:`~autodist_tpu.analysis.swap_conformance` so one dump replay
    covers both the control-plane protocol and the epoch-swap
    handshake."""
    from autodist_tpu.analysis import swap_conformance
    from autodist_tpu.telemetry.flight import load_dump
    events, meta = load_dump(path)
    findings = check_events(events)
    findings.extend(swap_conformance.check_swap_events(events))
    return findings, meta


def analyze(paths):
    """The CLI entry (``tools/analyze.py --conformance <dump>...``):
    finding strings across every dump, each prefixed with its file."""
    findings = []
    for path in paths:
        try:
            fs, meta = check_dump(path)
        except (OSError, ValueError) as e:
            findings.append('%s: unreadable flight-recorder dump '
                            '(%s: %s)' % (path, type(e).__name__, e))
            continue
        ctx = meta.get('context', {})
        findings.extend('%s [%s/%s]: %s'
                        % (path, ctx.get('ns', '?'),
                           ctx.get('worker', '?'), f) for f in fs)
    return findings
