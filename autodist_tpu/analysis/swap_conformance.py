"""Epoch-swap trace conformance: replay the flight recorder's
``swap_*`` events through the invariants the epoch-swap model proves.

:mod:`~autodist_tpu.analysis.epoch_swap_model` verifies the ABSTRACT
stage -> ack-quorum -> arm -> boundary-apply ordering (and shows the
tempting shortcuts corrupt state); :mod:`~autodist_tpu.runtime.session`
implements it through the :mod:`~autodist_tpu.runtime.swap_keys`
schema and records every handshake action in the crash flight
recorder (``swap_stage``, ``swap_ack``, ``swap_nack``, ``swap_arm``,
``swap_cancel``, ``swap_apply``). This checker closes the loop the
same way :mod:`~autodist_tpu.analysis.conformance` does for the
control-plane protocol: a recorded trace is ONE interleaving — the
one that happened — and it must satisfy the model's orderings.

Invariants (a flight ring is PER-PROCESS, so each rule is judged only
when the trace itself contains both halves — a peer's ring holds its
ack/apply but not the chief's stage/arm):

- **stage monotonicity** (``swap-gen-regression``) — staged
  generations strictly increase; a re-stage after cancel is a NEW
  generation (exactly-one-visible hygiene).
- **arm follows stage** (``arm-without-stage``) — the chief records
  stage and arm from the same handshake thread, so an armed
  generation with no retained stage on an untruncated ring means the
  implementation armed a plan it never staged.
- **no arm past a rejection** (``arm-after-nack`` /
  ``arm-after-cancel``) — a NACK or cancel ends the generation; an
  arm recorded after either for the same generation is the
  SWAP_BEFORE_ACK_QUORUM ordering the model counterexamples (a
  nacked member would be swapped past).
- **boundary respected** (``apply-before-boundary``) — every
  ``swap_apply`` is self-describing (step + boundary): applying
  before the armed boundary is the NAIVE_BOUNDARY mixed-plan-step.
- **one boundary per generation** (``boundary-mismatch``) — every
  member of a generation must observe the SAME armed boundary; and
  an apply after the generation was cancelled (``apply-after-cancel``)
  means a member committed a plan the chief withdrew.
- **per-worker apply monotonicity** (``apply-regression``) — a
  worker applies generations in increasing order (the session's
  ``_swap_applied_gen`` guard).
- **ack/nack exclusivity** (``ack-nack-conflict``) — one worker gives
  one verdict per generation.

Static-analysis wiring (``tools/analyze.py --swap-conformance``, part
of ``--all``): with no live dump at hand, :func:`analyze` replays a
synthetic verified trace (must be clean), replays seeded bad traces —
trace-level manifestations of the model's two seeded orderings —
which must each produce their finding (the sensitivity guard), and
pins ``swap_keys.MODEL_SYMBOLS`` against the model source: every
abstract symbol the model transitions on must be claimed by exactly
one shipped key template, so renaming either side is a finding, not
silent drift.
"""
import os
import re

_SWAP_KINDS = ('swap_stage', 'swap_ack', 'swap_nack', 'swap_arm',
               'swap_cancel', 'swap_apply')


def _fmt(ev, kind, msg):
    who = ev.get('worker', ev.get('by', '?'))
    return ('swap conformance [%s] at event #%s (%s %s): %s'
            % (kind, ev.get('seq', '?'), ev.get('kind', '?'), who,
               msg))


def check_swap_events(events):
    """Replay one recorded event sequence's ``swap_*`` events; returns
    finding strings (empty = the trace conforms to the epoch-swap
    model's orderings)."""
    findings = []

    def fresh():
        return {'staged': {},      # gen -> seq of swap_stage
                'armed': {},       # gen -> boundary of swap_arm
                'dead': {},        # gen -> seq of nack/cancel
                'verdict': {},     # (gen, worker) -> 'ack'|'nack'
                'applied': {},     # worker -> last applied gen
                'last_stage': 0}
    st = fresh()
    truncated = bool(events) and events[0].get('seq', 1) > 1
    for ev in events:
        kind = ev.get('kind', '')
        if kind == 'run_start':
            # same contract as conformance.check_events: the ring is
            # process-wide; a retained run_start both resets per-run
            # tracking and ends truncation for everything after it
            st = fresh()
            truncated = False
            continue
        if kind not in _SWAP_KINDS:
            continue
        gen = ev.get('gen')
        if not isinstance(gen, int) or gen < 1:
            findings.append(_fmt(
                ev, 'malformed-swap-event',
                "swap event carries no positive integer 'gen' field — "
                'the trace is truncated or was edited; generation '
                'invariants cannot be attributed'))
            continue
        if kind == 'swap_stage':
            if gen <= st['last_stage']:
                findings.append(_fmt(
                    ev, 'swap-gen-regression',
                    'staged generation %d after generation %d — '
                    'generations are monotone (a re-stage after '
                    'cancel is a NEW generation; exactly one staged '
                    'generation is ever visible)'
                    % (gen, st['last_stage'])))
            st['last_stage'] = max(st['last_stage'], gen)
            st['staged'][gen] = ev.get('seq')
            continue
        if kind in ('swap_ack', 'swap_nack'):
            w = ev.get('worker', '?')
            verdict = 'ack' if kind == 'swap_ack' else 'nack'
            prev = st['verdict'].get((gen, w))
            if prev is not None and prev != verdict:
                findings.append(_fmt(
                    ev, 'ack-nack-conflict',
                    'worker %s recorded both an ACK and a NACK for '
                    'generation %d — one worker gives one verdict per '
                    'staged generation' % (w, gen)))
            st['verdict'][(gen, w)] = verdict
            if kind == 'swap_nack':
                st['dead'].setdefault(gen, ev.get('seq'))
            continue
        if kind == 'swap_cancel':
            st['dead'].setdefault(gen, ev.get('seq'))
            continue
        if kind == 'swap_arm':
            if gen in st['dead']:
                reason = 'arm-after-nack' \
                    if any(v == 'nack' and g == gen
                           for (g, _w), v in st['verdict'].items()) \
                    else 'arm-after-cancel'
                findings.append(_fmt(
                    ev, reason,
                    'generation %d was armed AFTER its rejection '
                    '(event #%s) — arming without the full ack quorum '
                    'is the SWAP_BEFORE_ACK_QUORUM ordering: a nacked '
                    'member is swapped past and keeps pushing under '
                    'the old plan (epoch_swap_model mixed-plan-step)'
                    % (gen, st['dead'][gen])))
            elif gen not in st['staged'] and not truncated:
                # absence-based: stage and arm are recorded by the
                # same chief thread, so on an untruncated ring a
                # missing stage is real, not scroll-off
                findings.append(_fmt(
                    ev, 'arm-without-stage',
                    'generation %d was armed but never staged — peers '
                    'cannot have validated a plan that was never '
                    'published' % gen))
            st['armed'][gen] = ev.get('boundary', 0)
            continue
        # swap_apply
        w = ev.get('worker', '?')
        boundary = ev.get('boundary', 0)
        step = ev.get('step', 0)
        if step < boundary:
            findings.append(_fmt(
                ev, 'apply-before-boundary',
                'worker %s applied generation %d at step %d, BEFORE '
                'the armed boundary %d — the NAIVE_BOUNDARY ordering: '
                'a member crossing early executes a step the rest of '
                'the cohort runs under the other plan '
                '(epoch_swap_model mixed-plan-step)'
                % (w, gen, step, boundary)))
        if gen in st['armed'] and boundary != st['armed'][gen]:
            findings.append(_fmt(
                ev, 'boundary-mismatch',
                'worker %s applied generation %d with boundary %d but '
                'the trace armed boundary %d — every member of a '
                'generation must observe ONE boundary'
                % (w, gen, boundary, st['armed'][gen])))
        if gen in st['dead']:
            findings.append(_fmt(
                ev, 'apply-after-cancel',
                'worker %s applied generation %d, which was '
                'nacked/cancelled at event #%s — a cancelled stage '
                'must never commit' % (w, gen, st['dead'][gen])))
        if gen <= st['applied'].get(w, 0):
            findings.append(_fmt(
                ev, 'apply-regression',
                'worker %s applied generation %d after generation %d '
                '— a worker applies generations in increasing order'
                % (w, gen, st['applied'].get(w, 0))))
        st['applied'][w] = max(st['applied'].get(w, 0), gen)
    return findings


def check_dump(path):
    """Load a flight-recorder dump and run the swap checks; returns
    ``(findings, meta)``."""
    from autodist_tpu.telemetry.flight import load_dump
    events, meta = load_dump(path)
    return check_swap_events(events), meta


# -- key-schema pin -------------------------------------------------------

def _model_source():
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'epoch_swap_model.py')
    with open(path) as f:
        return f.read()


def check_schema_pin(model_src=None):
    """Pin the shipped key schema against the verified model's symbol
    table: every abstract ``swap/*`` symbol the model's transition
    functions touch must be claimed by exactly one
    ``swap_keys.MODEL_SYMBOLS`` template, and every claimed symbol
    must still exist in the model source — renaming either side is a
    finding, not silent drift. Returns finding strings."""
    from autodist_tpu.runtime import swap_keys
    src = _model_source() if model_src is None else model_src
    # symbols the model actually transitions on: swap/* literals in
    # CODE (strip comments/docstrings so prose can't satisfy the pin)
    code = re.sub(r'""".*?"""', '', src, flags=re.S)
    code = re.sub(r'#[^\n]*', '', code)
    model_syms = set(re.findall(r"'(swap/[A-Za-z+]+)'", code))
    findings = []
    claimed = {}
    for tmpl, sym in swap_keys.MODEL_SYMBOLS.items():
        if sym in claimed:
            findings.append(
                'swap_keys.MODEL_SYMBOLS: templates %s and %s both '
                'claim model symbol %s — the mapping must stay '
                'one-to-one' % (claimed[sym], tmpl, sym))
            continue
        claimed[sym] = tmpl
    for sym in sorted(model_syms - set(claimed)):
        findings.append(
            'epoch_swap_model transitions on symbol %s but no '
            'swap_keys.MODEL_SYMBOLS template claims it — the shipped '
            'key schema no longer covers the verified ordering' % sym)
    for sym in sorted(set(claimed) - model_syms):
        findings.append(
            'swap_keys.MODEL_SYMBOLS claims model symbol %s (template '
            '%s) which epoch_swap_model no longer transitions on — '
            'stale mapping, or the model was renamed without the '
            'schema' % (sym, claimed[sym]))
    return findings


# -- static-analysis entry ------------------------------------------------

def _verified_trace():
    """A synthetic trace of the verified ordering, including a
    NACK -> cancel -> re-stage retry: must replay clean."""
    return [
        {'seq': 1, 'kind': 'run_start'},
        {'seq': 2, 'kind': 'swap_stage', 'gen': 1, 'world': 3},
        {'seq': 3, 'kind': 'swap_nack', 'gen': 1, 'worker': 'p1',
         'reason': 'cannot apply'},
        {'seq': 4, 'kind': 'swap_cancel', 'gen': 1, 'reason': 'nack'},
        {'seq': 5, 'kind': 'swap_stage', 'gen': 2, 'world': 3},
        {'seq': 6, 'kind': 'swap_ack', 'gen': 2, 'worker': 'p1'},
        {'seq': 7, 'kind': 'swap_arm', 'gen': 2, 'boundary': 7,
         'floor': 4},
        {'seq': 8, 'kind': 'swap_apply', 'gen': 2, 'worker': 'p0',
         'boundary': 7, 'step': 7},
        {'seq': 9, 'kind': 'swap_apply', 'gen': 2, 'worker': 'p1',
         'boundary': 7, 'step': 8},
    ]


#: Seeded bad traces — trace-level manifestations of the model's
#: seeded wrong orderings (and the hygiene rules). Each must produce
#: its named finding or the checker has gone blind (the same
#: sensitivity contract as the model checkers' SEEDED_BUGS).
SEEDED_TRACES = (
    ('arm past a NACK (SWAP_BEFORE_ACK_QUORUM)', 'arm-after-nack', [
        {'seq': 1, 'kind': 'run_start'},
        {'seq': 2, 'kind': 'swap_stage', 'gen': 1, 'world': 3},
        {'seq': 3, 'kind': 'swap_nack', 'gen': 1, 'worker': 'p1',
         'reason': 'cannot apply'},
        {'seq': 4, 'kind': 'swap_arm', 'gen': 1, 'boundary': 5,
         'floor': 2},
    ]),
    ('apply before the armed boundary (NAIVE_BOUNDARY)',
     'apply-before-boundary', [
         {'seq': 1, 'kind': 'run_start'},
         {'seq': 2, 'kind': 'swap_stage', 'gen': 1, 'world': 3},
         {'seq': 3, 'kind': 'swap_ack', 'gen': 1, 'worker': 'p1'},
         {'seq': 4, 'kind': 'swap_arm', 'gen': 1, 'boundary': 6,
          'floor': 3},
         {'seq': 5, 'kind': 'swap_apply', 'gen': 1, 'worker': 'p1',
          'boundary': 6, 'step': 5},
     ]),
    ('re-stage without bumping the generation', 'swap-gen-regression', [
        {'seq': 1, 'kind': 'run_start'},
        {'seq': 2, 'kind': 'swap_stage', 'gen': 2, 'world': 3},
        {'seq': 3, 'kind': 'swap_cancel', 'gen': 2,
         'reason': 'ack_timeout'},
        {'seq': 4, 'kind': 'swap_stage', 'gen': 2, 'world': 3},
    ]),
    ('apply of a cancelled generation', 'apply-after-cancel', [
        {'seq': 1, 'kind': 'run_start'},
        {'seq': 2, 'kind': 'swap_stage', 'gen': 1, 'world': 3},
        {'seq': 3, 'kind': 'swap_cancel', 'gen': 1, 'reason': 'nack'},
        {'seq': 4, 'kind': 'swap_apply', 'gen': 1, 'worker': 'p1',
         'boundary': 4, 'step': 4},
    ]),
)


def analyze(paths=None):
    """The static-analysis entry (``tools/analyze.py
    --swap-conformance``, part of ``--all``): the synthetic verified
    trace must replay clean, every seeded bad trace must produce its
    finding, and the shipped key schema must pin to the model's symbol
    table. With ``paths``, additionally replays those dumps (the
    operator CLI path). Returns finding strings (empty = clean)."""
    findings = []
    clean = check_swap_events(_verified_trace())
    findings.extend('verified synthetic trace does not replay clean: '
                    + f for f in clean)
    for label, expect, trace in SEEDED_TRACES:
        got = check_swap_events(trace)
        if not any('[%s]' % expect in f for f in got):
            findings.append(
                'sensitivity guard: seeded trace %r no longer yields '
                'a [%s] finding (got: %s) — the swap-conformance '
                'checker has gone blind to an ordering the model '
                'counterexamples' % (label, expect, got or 'clean'))
    findings.extend(check_schema_pin())
    for path in paths or ():
        try:
            fs, meta = check_dump(path)
        except (OSError, ValueError) as e:
            findings.append('%s: unreadable flight-recorder dump '
                            '(%s: %s)' % (path, type(e).__name__, e))
            continue
        ctx = meta.get('context', {})
        findings.extend('%s [%s/%s]: %s'
                        % (path, ctx.get('ns', '?'),
                           ctx.get('worker', '?'), f) for f in fs)
    return findings
