"""Schedule/plan consistency lint — shape algebra over the IR.

The simulator prices the collective schedule
``static_collective_schedule`` derives WITHOUT tracing; the runtime
emits the schedule ``ExecutionPlan.sync_gradients`` derives WHILE
tracing. Since the schedule-IR refactor both derive from the SAME
program (``schedule_ir.bucket_program`` builds it, ``schedule_entry``
projects the static entry, ``execute`` drives the traced emission), so
predicted == traced is structural and the old N per-predicate AST
cross-checks collapse into two much stronger checks:

- **IR shape algebra, run ONCE** (:func:`check_ir_algebra`): every
  dimension combination the emitters can produce — flat vs two-level,
  the int8 tier boundary, ZeRO scatter/gather halves, weight-update
  sharding, sparse rows — is built through the shared lowering over
  dividing, non-dividing and padded sizes and verified by
  :func:`schedule_ir.verify`: groups partition the mesh, chunks tile
  their spans, byte flow conserves across requantize boundaries, and
  the final per-device partition matches the declared goal. A seeded
  WRONG schedule (the int8 boundary requantize moved inside the ICI
  phase) must still produce its finding — the same sensitivity guard
  the model checkers carry (``analysis/explore.py`` SEEDED_BUGS): an
  algebra that stops flagging the counterexample fails here, not
  silently.
- **a thin routes-through-the-IR drift check**
  (:func:`check_emission_predicates`): both emitters must fuse through
  the shared ``bucket_fusable`` / ``bucket_fusion_key`` predicates with
  identical call shapes, pack via ``pack_buckets`` in the same
  reverse-production order, lower through ``bucket_program`` +
  ``schedule_entry``, and the traced side must EXECUTE through
  ``schedule_ir.execute`` (an emission helper hand-rolling a collective
  again would bypass everything the algebra proves). The shared
  flat-vs-two-level (``choose_hierarchical``) and update-sharding
  (``choose_update_sharding``) decisions must still be consulted on
  both sides with the same call shape.

Also here:

- **pricing parity** (:func:`check_pricing_parity`):
  ``cost_model.program_time`` over the lowered IR must agree with the
  closed-form ``entry_time`` on the legacy shapes — the bridge that
  lets synthesis rank hand-written and synthesized programs on one
  scale;
- **reshard shape algebra** — ``reshard.plan_reshard`` layout moves
  are verified over a synthetic geometry sweep (every src/dst layout
  pair across dividing, non-dividing and padded shapes): op-kind
  preconditions, destination-shard partition exactness, zero-wire
  claims, AND each op's own IR program (``ReshardOp.ir_program``)
  verifies clean through the same algebra the gradient schedules use;
- the absorbed ``tools/check_wire_pricing.py`` drift check (compressor
  registry vs ``cost_model._WIRE_ITEMSIZE``).
"""
import ast
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PLAN_SRC = os.path.join(REPO, 'autodist_tpu', 'parallel', 'plan.py')

# -- IR shape algebra (the ONE verification pass) -------------------------

#: (kind, compressor, hier, wus) dimension combinations the emitters
#: can produce — the five legacy schedule dimensions as IR lowerings.
_IR_COMBOS = tuple(
    [(kind, cname, hier, False)
     for kind in ('all_reduce', 'psum_scatter', 'all_gather')
     for cname in (None, 'HorovodCompressor', 'Int8RingCompressor')
     for hier in (0, 2, 4)] +
    [(kind, cname, hier, True)                 # weight-update sharding
     for kind in ('psum_scatter', 'all_gather')
     for cname in (None, 'Int8RingCompressor')
     for hier in (0, 2)] +
    [(kind, None, 0, False)                    # sparse rows
     for kind in ('sparse_all_gather', 'sparse_scatter')])

#: raw byte sizes: dividing (1024 f32 elems over 8 devices),
#: non-dividing (1000 elems -> internal padding), and prime-odd.
_IR_SIZES = (4096, 4000, 1972)


def check_ir_algebra(n=8):
    """Build every emitter-reachable dimension combination through the
    shared lowering and run the shape algebra on it. Any finding means
    an emitter change produced a schedule that loses, duplicates or
    mis-wires elements — caught structurally, regardless of fixture
    coverage."""
    from autodist_tpu.parallel import schedule_ir as sir
    findings = []
    for kind, cname, hier, wus in _IR_COMBOS:
        for nbytes in _IR_SIZES:
            try:
                prog = sir.bucket_program(
                    kind, nbytes, 'float32', cname, 'AUTO', n,
                    hier=hier, wus=wus)
            except ValueError as err:
                findings.append(
                    'schedule-ir lowering (%s, %s, hier=%d, wus=%s, '
                    '%dB) refused to build: %s'
                    % (kind, cname, hier, wus, nbytes, err))
                continue
            for f in sir.verify(prog):
                findings.append('%s [from (%s, %s, hier=%d, wus=%s, '
                                '%dB)]' % (f, kind, cname, hier, wus,
                                           nbytes))
    findings.extend(check_ir_sensitivity(n))
    return findings


def seeded_counterexample(n=8):
    """A deliberately WRONG schedule: the int8 tier-boundary program
    with its down-requantize moved INSIDE the ICI phase — the
    reduce-scatter then declares an f32 wire while the live buffer is
    already i8, exactly the mis-placed boundary the byte-flow /
    wire-state rules exist to catch."""
    from autodist_tpu.parallel import schedule_ir as sir
    prog = sir.bucket_program('all_reduce', 1 << 16, 'float32',
                              'Int8RingCompressor', 'AUTO', n, hier=2)
    steps = list(prog.steps)
    for i, s in enumerate(steps):
        if s.op == 'requantize' and s.wire == 'i8' and i > 0:
            steps[i - 1], steps[i] = steps[i], steps[i - 1]
            break
    return sir.Program(prog.name + '/seeded-bad', prog.n, prog.elems,
                       prog.dtype, tuple(steps), prog.init, prog.goal,
                       dict(prog.meta))


def check_ir_sensitivity(n=8):
    """The sensitivity guard: the seeded wrong schedule must still be
    flagged, or the algebra's clean HEAD run proves nothing."""
    from autodist_tpu.parallel import schedule_ir as sir
    bad = seeded_counterexample(n)
    if not sir.verify(bad):
        return ['schedule-ir sensitivity guard: the seeded wrong '
                'schedule (int8 requantize inside the ICI phase) '
                'verifies CLEAN — the algebra lost the sensitivity '
                'that justifies trusting its clean HEAD run']
    return []


# -- thin routes-through-the-IR drift check -------------------------------

def _functions(tree):
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def _calls_of(fn, src, callee):
    """(positional arg count, sorted kwarg names) per call of
    ``callee`` inside ``fn``."""
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else \
            getattr(f, 'id', '')
        if name == callee:
            out.append((len(node.args),
                        tuple(sorted(k.arg for k in node.keywords
                                     if k.arg))))
    return out


def _sort_key(fn, src):
    """The canonical source of the ``pending.sort(key=...)`` lambda —
    the reverse-production emission order both sides must share."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == 'sort':
            for kw in node.keywords:
                if kw.arg == 'key':
                    return re.sub(
                        r'\s+', '',
                        ast.get_source_segment(src, kw.value) or '')
    return None


#: traced-emission helpers that must EXECUTE through the IR — a helper
#: dispatching a collective without ``schedule_ir.execute`` bypasses
#: the algebra, the pricing bridge and the entry-id join at once.
_TRACED_EXECUTORS = ('_reduce_fn', '_capped_psum_scatter',
                     '_int8_bucket_reduce', '_wus_scatter_bucket',
                     'gather_updated_params')


def check_emission_predicates(src=None):
    """Cross-check that sync_gradients and static_collective_schedule
    both route through the ONE shared IR lowering (and the shared
    fusion / hierarchy / update-sharding decisions)."""
    if src is None:
        with open(PLAN_SRC) as f:
            src = f.read()
    findings = []
    fns = _functions(ast.parse(src))
    traced = fns.get('sync_gradients')
    static = fns.get('static_collective_schedule')
    hier = fns.get('_hier_groups_for')
    if traced is None or static is None:
        return ['plan.py: sync_gradients/static_collective_schedule '
                'not found — update analysis/schedule_lint.py for the '
                'new layout']
    # the shared fusion predicates: both sides must consult the same
    # bucket_fusable / bucket_fusion_key with the same call shape
    for callee, what in (('bucket_fusable', 'fusable predicate'),
                         ('bucket_fusion_key', 'fusion key')):
        tc = _calls_of(traced, src, callee)
        sc = _calls_of(static, src, callee)
        if not tc or not sc:
            findings.append(
                'plan.py: the bucket %s must route through the ONE '
                'shared %s on both sides (traced call missing: %s, '
                'static call missing: %s) — an inline predicate '
                'reintroduces exactly the per-side drift the IR '
                'refactor removed' % (what, callee, not tc, not sc))
        elif set(tc) != set(sc):
            findings.append(
                'plan.py: %s call shapes DRIFTED — traced %s vs '
                'static %s: the simulator would price buckets the '
                'runtime never emits' % (callee, tc, sc))
    # the shared lowering: both sides must build programs via
    # bucket_program and project entries via schedule_entry
    for name, fn in (('sync_gradients', traced),
                     ('static_collective_schedule', static)):
        for callee in ('pack_buckets', 'bucket_program',
                       'schedule_entry'):
            if not _calls_of(fn, src, callee):
                findings.append(
                    'plan.py: %s no longer routes through %s — the '
                    'two emission paths must derive from the SAME IR '
                    'program' % (name, callee))
    # the traced side must EXECUTE through the IR interpreter
    for helper in _TRACED_EXECUTORS:
        fn = fns.get(helper)
        if fn is None:
            findings.append(
                'plan.py: traced emission helper %s missing — the '
                'schedule the simulator prices no longer exists'
                % helper)
        elif not _calls_of(fn, src, 'execute'):
            findings.append(
                'plan.py: %s no longer executes through '
                'schedule_ir.execute — a hand-rolled collective '
                'bypasses the verified lowering' % helper)
    if not _calls_of(traced, src, '_wus_scatter_bucket'):
        findings.append(
            'plan.py: sync_gradients no longer dispatches '
            'update-sharded buckets through _wus_scatter_bucket')
    # shared flat-vs-two-level decision
    traced_hier = _calls_of(hier, src, 'choose_hierarchical') \
        if hier is not None else []
    static_hier = _calls_of(static, src, 'choose_hierarchical')
    if not traced_hier or not static_hier:
        findings.append(
            'plan.py: the flat-vs-hierarchical decision must route '
            'through the ONE shared cost_model.choose_hierarchical on '
            'both sides (traced call missing: %s, static call missing: '
            '%s)' % (not traced_hier, not static_hier))
    elif set(traced_hier) != set(static_hier):
        findings.append(
            'plan.py: choose_hierarchical call shapes DRIFTED — traced '
            '%s vs static %s (same positional arity + kwargs required, '
            'or the two sides price different decisions)'
            % (traced_hier, static_hier))
    # shared update-sharding decision; an emission that never CONSULTS
    # the helper decides nothing
    wus_helper = fns.get('_wus_for')
    traced_wus = _calls_of(wus_helper, src, 'choose_update_sharding') \
        if wus_helper is not None else []
    if not _calls_of(traced, src, '_wus_for'):
        traced_wus = []
    static_wus = _calls_of(static, src, 'choose_update_sharding')
    if not traced_wus or not static_wus:
        findings.append(
            'plan.py: the replicated-vs-sharded weight-update decision '
            'must route through the ONE shared '
            'cost_model.choose_update_sharding on both sides (traced '
            'call missing: %s, static call missing: %s)'
            % (not traced_wus, not static_wus))
    elif set(traced_wus) != set(static_wus):
        findings.append(
            'plan.py: choose_update_sharding call shapes DRIFTED — '
            'traced %s vs static %s (same positional arity + kwargs '
            'required, or the slot placement, traced emission and '
            'priced schedule decide differently)'
            % (traced_wus, static_wus))
    # the static update-shard pair must survive as IR lowerings
    static_src = re.sub(r'\s+', '',
                        ast.get_source_segment(src, static) or '')
    for token, what in (
            ("('psum_scatter','grad')", 'grad-phase reduce-scatter'),
            ("('all_gather','param')", 'param-phase all-gather'),
            ('wus=True', 'wus tag')):
        if token not in static_src:
            findings.append(
                'plan.py: static_collective_schedule no longer emits '
                'the update-shard %s entry (%s) — the simulator would '
                'price a schedule without the update-sharding halves'
                % (what, token))
    tso, sso = _sort_key(traced, src), _sort_key(static, src)
    if tso != sso:
        findings.append(
            'plan.py: bucket emission order DRIFTED — sync_gradients '
            'sorts by %r, static_collective_schedule by %r' % (tso,
                                                               sso))
    return findings


# -- pricing parity: program_time over the IR == entry_time ---------------

def check_pricing_parity(n=8, nodes=2):
    """``cost_model.program_time`` over the lowered IR must agree with
    the closed-form ``entry_time`` on every legacy shape — the scale
    synthesis ranks hand-written and synthesized candidates on."""
    from autodist_tpu.parallel import schedule_ir as sir
    from autodist_tpu.simulator import cost_model
    params = cost_model.CostModelParams()
    findings = []
    shapes = [('all_reduce', None, 0), ('all_reduce', None, nodes),
              ('all_reduce', 'HorovodCompressor', 0),
              ('all_reduce', 'Int8RingCompressor', 0),
              ('all_reduce', 'Int8RingCompressor', nodes),
              ('psum_scatter', None, 0), ('psum_scatter', None, nodes),
              ('all_gather', None, 0), ('all_gather', None, nodes),
              ('sparse_all_gather', None, 0)]
    for kind, cname, hier in shapes:
        nbytes = 1 << 16
        entry = {'kind': kind, 'bytes': nbytes, 'dtype': 'float32',
                 'compressor': cname, 'spec': 'AUTO', 'vars': 1,
                 'hier': hier, 'members': ['v']}
        want, _ = cost_model.entry_time(entry, n, params,
                                        cross_node=True)
        prog = sir.bucket_program(kind, nbytes, 'float32', cname,
                                  'AUTO', n, hier=hier)
        got = cost_model.program_time(prog, params)
        tol = max(1e-12, 1e-6 * abs(want))
        if abs(got - want) > tol:
            findings.append(
                'pricing parity DRIFTED for (%s, %s, hier=%d): '
                'program_time %.6g s vs entry_time %.6g s — synthesis '
                'would rank hand-written schedules on a different '
                'scale than the simulator prices them'
                % (kind, cname, hier, got, want))
    return findings


# -- reshard shape algebra ------------------------------------------------

def _layouts_for(shape, n):
    """Every layout an ExecutionPlan can place a var of ``shape`` in on
    an ``n``-way data axis, with the plan's padding rule."""
    outs = [{'sharded': False, 'axis': None, 'padded_dim': None,
             'pad': 0}]
    for axis, dim in enumerate(shape):
        if dim < n:
            continue   # the plan only shards axes >= n
        padded = -(-dim // n) * n
        outs.append({'sharded': True, 'axis': axis,
                     'padded_dim': padded, 'pad': padded - dim})
    return outs


def _holdings(layout, shape, n, d):
    """The logical flat-index set device ``d`` holds under ``layout``
    (pad rows excluded)."""
    import numpy as np
    idx = np.arange(int(np.prod(shape))).reshape(shape)
    if not layout['sharded']:
        return set(idx.ravel().tolist())
    ax, dim = layout['axis'], shape[layout['axis']]
    rows = layout['padded_dim'] // n
    lo, hi = d * rows, min((d + 1) * rows, dim)
    if lo >= dim:
        return set()
    sl = [slice(None)] * len(shape)
    sl[ax] = slice(lo, hi)
    return set(idx[tuple(sl)].ravel().tolist())


def _mock_plan(shape, layout, n):
    from types import SimpleNamespace
    import numpy as np
    var = SimpleNamespace(shape=tuple(shape), dtype=np.float32)
    vp = SimpleNamespace(var=var, state_sharded=layout['sharded'],
                         shard_axis=layout['axis'] or 0,
                         padded_dim=layout['padded_dim'],
                         pad=layout['pad'])
    return SimpleNamespace(var_plans={'v': vp}, num_replicas=n,
                           cost_params=None)


def check_reshard_algebra():
    """Element-preservation + op-kind preconditions over the sweep,
    with every planned op ALSO verified through its own IR program —
    reshard and gradient sync now answer to the same algebra."""
    from autodist_tpu.parallel import reshard
    from autodist_tpu.parallel import schedule_ir as sir
    from autodist_tpu.simulator.cost_model import CostModelParams
    import numpy as np
    params = CostModelParams()
    findings = []
    shapes = [(8,), (8, 4), (9, 4), (8, 6), (6, 10)]
    for n in (2, 4):
        for shape in shapes:
            for src in _layouts_for(shape, n):
                for dst in _layouts_for(shape, n):
                    old = _mock_plan(shape, src, n)
                    new = _mock_plan(shape, dst, n)
                    ops = reshard.plan_reshard(old, new, params=params)
                    if len(ops) != 1:
                        findings.append(
                            'reshard: plan for %s n=%d covered %d ops '
                            'for 1 var' % (shape, n, len(ops)))
                        continue
                    op = ops[0]
                    ctx = 'reshard %s n=%d %s->%s (%s)' % (
                        shape, n, _fmt(src), _fmt(dst), op.kind)
                    findings.extend(_check_op(op, src, dst, shape, n,
                                              ctx))
                    elems = int(np.prod(shape))
                    for f in sir.verify(op.ir_program(n, elems)):
                        findings.append('%s: %s' % (ctx, f))
    return findings


def _fmt(layout):
    if not layout['sharded']:
        return 'repl'
    return 'shard(ax%d,pad%d)' % (layout['axis'], layout['pad'])


def _check_op(op, src, dst, shape, n, ctx):
    problems = []
    # kind preconditions (the shape algebra each lowering requires)
    if op.kind == 'noop' and src != dst:
        problems.append('%s: noop chosen for a layout CHANGE' % ctx)
    if op.kind != 'noop' and src == dst:
        problems.append('%s: layout unchanged but op is not noop' % ctx)
    if op.kind == 'shard' and (src['sharded'] or not dst['sharded']):
        problems.append('%s: shard requires replicated->sharded' % ctx)
    if op.kind == 'all_gather' and (not src['sharded']
                                    or dst['sharded']):
        problems.append('%s: all_gather requires sharded->replicated'
                        % ctx)
    if op.kind == 'all_to_all':
        if not (src['sharded'] and dst['sharded']):
            problems.append('%s: all_to_all requires sharded->sharded'
                            % ctx)
        elif src['pad'] or dst['pad'] or src['axis'] == dst['axis']:
            problems.append(
                '%s: all_to_all chosen where its tiled split cannot '
                'lower (pad %d->%d, axis %s->%s)'
                % (ctx, src['pad'], dst['pad'], src['axis'],
                   dst['axis']))
    for layout, which in ((src, 'src'), (dst, 'dst')):
        if layout['sharded']:
            dim = shape[layout['axis']]
            if layout['padded_dim'] % n:
                problems.append('%s: %s padded_dim %d not divisible by '
                                'n=%d' % (ctx, which,
                                          layout['padded_dim'], n))
            if layout['padded_dim'] - layout['pad'] != dim:
                problems.append('%s: %s pad algebra broken (padded %d '
                                '- pad %d != dim %d)'
                                % (ctx, which, layout['padded_dim'],
                                   layout['pad'], dim))
    # element preservation: dst shards partition the logical set
    import numpy as np
    everything = set(range(int(np.prod(shape))))
    union, total = set(), 0
    for d in range(n):
        h = _holdings(dst, shape, n, d)
        union |= h
        total += len(h)
    if union != everything:
        problems.append('%s: destination layout LOSES elements (%d of '
                        '%d reachable)' % (ctx, len(union),
                                           len(everything)))
    if dst['sharded'] and total != len(everything):
        problems.append('%s: destination shards overlap (%d held vs '
                        '%d logical)' % (ctx, total, len(everything)))
    if op.kind in ('noop', 'shard') and op.wire_bytes:
        problems.append('%s: zero-wire kind claims %d wire bytes'
                        % (ctx, op.wire_bytes))
    if op.est_time_s < 0:
        problems.append('%s: negative cost estimate' % ctx)
    return problems


# -- absorbed wire-pricing drift check ------------------------------------

def check_wire_pricing():
    """Compressor registry vs cost_model._WIRE_ITEMSIZE (a compressor
    missing from the table silently prices as f32)."""
    from autodist_tpu.parallel.compressor import _REGISTRY
    from autodist_tpu.simulator.cost_model import _WIRE_ITEMSIZE
    registry, priced = set(_REGISTRY), set(_WIRE_ITEMSIZE)
    problems = []
    for name in sorted(registry - priced):
        problems.append('compressor registered but missing from '
                        'cost_model._WIRE_ITEMSIZE (would silently '
                        'price as f32): %s' % name)
    for name in sorted(priced - registry):
        problems.append('priced in cost_model._WIRE_ITEMSIZE but not '
                        'in the compressor registry (stale entry): %s'
                        % name)
    if not registry:
        problems.append('compressor registry is empty — the registry '
                        'moved or the import graph broke')
    return problems


def analyze():
    """Run all schedule/plan consistency checks. Returns finding
    strings (empty = clean)."""
    return (check_ir_algebra() + check_emission_predicates() +
            check_pricing_parity() + check_reshard_algebra() +
            check_wire_pricing())
