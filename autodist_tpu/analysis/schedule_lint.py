"""Schedule/plan consistency lint.

The simulator prices the collective schedule
``static_collective_schedule`` derives WITHOUT tracing; the runtime
emits the schedule ``ExecutionPlan.sync_gradients`` derives WHILE
tracing. The two are pinned equal by a traced test on one fixture
(``tests/test_simulator.py``), but a predicate edited in only one of
them can drift on configurations the fixture does not cover — the
cost model would then price a schedule the runtime never runs (the
array-redistribution paper's core complaint about layout-move
programs, arXiv:2112.01075). This lint cross-checks the EMISSION
PREDICATES at the AST level, so any asymmetric edit fails tier-1
regardless of fixture coverage:

- the bucket-fusion key (group, compressor, dtype, spec, hierarchical
  knob, weight-update-sharding knob) must have identical canonical
  components in both functions;
- the fusable-predicate (which compressors may bucket-fuse, the
  ``int8_bucket_fusable`` escape hatch) must admit the same set;
- both sides must route the flat-vs-two-level choice through the ONE
  shared ``choose_hierarchical`` decision with the same signature;
- both sides must route the replicated-vs-sharded weight-update
  choice through the ONE shared ``choose_update_sharding`` decision
  with the same signature (traced: ``_wus_for``), and the
  update-shard emissions must exist on both sides: the traced
  reduce-scatter + bucketed param all-gather
  (``_wus_scatter_bucket`` / ``gather_updated_params``) and the
  static ``psum_scatter``/``all_gather`` pair tagged ``wus`` — an
  asymmetric edit (e.g. new emission traced but never priced) fails
  tier-1 here, not just on the fixture pin;
- both sides must pack with ``pack_buckets`` and emit in the same
  reverse-production order (the ``pending.sort`` key).

Also here:

- **reshard shape algebra** — ``reshard.plan_reshard`` layout moves
  are verified element-preserving over a synthetic geometry sweep
  (every src/dst layout pair across dividing, non-dividing and padded
  shapes): each op kind's preconditions hold (``all_to_all`` only on
  clean unpadded axis changes, etc.), the destination layout's shards
  partition exactly the logical element set (no loss, no overlap
  outside the pad), and zero-wire kinds claim zero wire;
- the absorbed ``tools/check_wire_pricing.py`` drift check (compressor
  registry vs ``cost_model._WIRE_ITEMSIZE``).
"""
import ast
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PLAN_SRC = os.path.join(REPO, 'autodist_tpu', 'parallel', 'plan.py')

# -- AST cross-check of the two emission paths ----------------------------

_CANON_RULES = (
    (r'type\(plan\.compressor\)\.__name__', 'COMPRESSOR'),
    (r'str\(np\.dtype\(var\.dtype\)\)', 'DTYPE'),
    (r'str\(grad\.dtype\)', 'DTYPE'),
    (r'plan\.group', 'GROUP'),
    (r'plan\.spec', 'SPEC'),
    (r'plan\.weight_update_sharding', 'WUS'),
    (r'plan\.hierarchical', 'HIER'),
)


def _canon(src, assigns):
    """Whitespace-free source with single-assignment names substituted
    and the known equivalent spellings mapped to canonical tokens."""
    def rules(s):
        for pat, token in _CANON_RULES:
            s = re.sub(pat, token, s)
        return s

    s = rules(re.sub(r'\s+', '', src))
    for _ in range(4):   # bounded transitive substitution
        out = s
        for name, val in assigns.items():
            out = re.sub(r'\b%s\b' % re.escape(name),
                         lambda m, val=val: rules(val), out)
        out = rules(out)
        if out == s:
            break
        s = out
    return s


def _functions(tree):
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def _assigns(fn, src):
    """Simple single-target name assignments inside ``fn`` (for
    substitution), by source text."""
    out = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            seg = ast.get_source_segment(src, node.value)
            if seg is not None:
                name = node.targets[0].id
                # only keep names assigned once (no reliable value
                # otherwise)
                out[name] = None if name in out \
                    else re.sub(r'\s+', '', seg)
    return {k: v for k, v in out.items() if v is not None}


def _fusion_key(fn, src):
    """The canonical components of ``key = (...)`` in ``fn``."""
    assigns = _assigns(fn, src)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == 'key' \
                and isinstance(node.value, ast.Tuple):
            return tuple(
                _canon(ast.get_source_segment(src, el), assigns)
                for el in node.value.elts)
    return None


def _fusable_compressors(fn, src):
    """The compressor classes the ``type(plan.compressor) in (...)``
    membership test admits, plus whether ``int8_bucket_fusable`` is
    consulted."""
    admitted, int8 = None, False
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare) and len(node.ops) == 1 and \
                isinstance(node.ops[0], ast.In):
            seg = re.sub(r'\s+', '',
                         ast.get_source_segment(src, node.left) or '')
            if seg == 'type(plan.compressor)' and \
                    isinstance(node.comparators[0], ast.Tuple):
                admitted = tuple(sorted(
                    (ast.get_source_segment(src, el) or '')
                    .split('.')[-1]
                    for el in node.comparators[0].elts))
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                getattr(f, 'id', '')
            if name == 'int8_bucket_fusable':
                int8 = True
    return admitted, int8


def _calls_of(fn, src, callee):
    """(positional arg count, sorted kwarg names) per call of
    ``callee`` inside ``fn``."""
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else \
            getattr(f, 'id', '')
        if name == callee:
            out.append((len(node.args),
                        tuple(sorted(k.arg for k in node.keywords
                                     if k.arg))))
    return out


def _sort_key(fn, src):
    """The canonical source of the ``pending.sort(key=...)`` lambda —
    the reverse-production emission order both sides must share."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == 'sort':
            for kw in node.keywords:
                if kw.arg == 'key':
                    return re.sub(
                        r'\s+', '',
                        ast.get_source_segment(src, kw.value) or '')
    return None


def check_emission_predicates(src=None):
    """Cross-check sync_gradients vs static_collective_schedule."""
    if src is None:
        with open(PLAN_SRC) as f:
            src = f.read()
    findings = []
    fns = _functions(ast.parse(src))
    traced = fns.get('sync_gradients')
    static = fns.get('static_collective_schedule')
    hier = fns.get('_hier_groups_for')
    if traced is None or static is None:
        return ['plan.py: sync_gradients/static_collective_schedule '
                'not found — update analysis/schedule_lint.py for the '
                'new layout']
    tk, sk = _fusion_key(traced, src), _fusion_key(static, src)
    if tk is None or sk is None:
        findings.append('plan.py: bucket-fusion key tuple not found in '
                        '%s' % ('sync_gradients' if tk is None
                                else 'static_collective_schedule'))
    elif tk != sk:
        findings.append(
            'plan.py: bucket-fusion keys DRIFTED — sync_gradients '
            'fuses on %s but static_collective_schedule on %s: the '
            'simulator would price buckets the runtime never emits'
            % (tk, sk))
    (ta, ti), (sa, si) = (_fusable_compressors(traced, src),
                          _fusable_compressors(static, src))
    if ta is None or sa is None:
        findings.append(
            'plan.py: fusable-compressor membership test '
            '(type(plan.compressor) in (...)) not found in %s'
            % ('sync_gradients' if ta is None
               else 'static_collective_schedule'))
    elif (ta, ti) != (sa, si):
        findings.append(
            'plan.py: fusable predicates DRIFTED — sync_gradients '
            'admits %s (int8 hatch: %s) but static_collective_schedule '
            'admits %s (int8 hatch: %s)' % (ta, ti, sa, si))
    traced_hier = _calls_of(hier, src, 'choose_hierarchical') \
        if hier is not None else []
    static_hier = _calls_of(static, src, 'choose_hierarchical')
    if not traced_hier or not static_hier:
        findings.append(
            'plan.py: the flat-vs-hierarchical decision must route '
            'through the ONE shared cost_model.choose_hierarchical on '
            'both sides (traced call missing: %s, static call missing: '
            '%s)' % (not traced_hier, not static_hier))
    elif set(traced_hier) != set(static_hier):
        findings.append(
            'plan.py: choose_hierarchical call shapes DRIFTED — traced '
            '%s vs static %s (same positional arity + kwargs required, '
            'or the two sides price different decisions)'
            % (traced_hier, static_hier))
    # weight-update sharding: ONE shared decision + both emission
    # halves present on both sides (the extension this lint grew for:
    # an update-shard/all-gather emission edited on one side only must
    # fail tier-1 regardless of fixture coverage)
    wus_helper = fns.get('_wus_for')
    traced_wus = _calls_of(wus_helper, src, 'choose_update_sharding') \
        if wus_helper is not None else []
    if not _calls_of(traced, src, '_wus_for'):
        # the helper may still carry the shared call, but an emission
        # that never CONSULTS it decides nothing
        traced_wus = []
    static_wus = _calls_of(static, src, 'choose_update_sharding')
    if not traced_wus or not static_wus:
        findings.append(
            'plan.py: the replicated-vs-sharded weight-update decision '
            'must route through the ONE shared '
            'cost_model.choose_update_sharding on both sides (traced '
            'call missing: %s, static call missing: %s)'
            % (not traced_wus, not static_wus))
    elif set(traced_wus) != set(static_wus):
        findings.append(
            'plan.py: choose_update_sharding call shapes DRIFTED — '
            'traced %s vs static %s (same positional arity + kwargs '
            'required, or the slot placement, traced emission and '
            'priced schedule decide differently)'
            % (traced_wus, static_wus))
    scatter_fn = fns.get('_wus_scatter_bucket')
    gather_fn = fns.get('gather_updated_params')
    if scatter_fn is None or gather_fn is None:
        findings.append(
            'plan.py: weight-update-shard emission halves missing '
            '(_wus_scatter_bucket: %s, gather_updated_params: %s) — '
            'the schedule the simulator prices no longer exists'
            % (scatter_fn is None, gather_fn is None))
    else:
        if not _calls_of(traced, src, '_wus_scatter_bucket'):
            findings.append(
                'plan.py: sync_gradients no longer dispatches '
                'update-sharded buckets through _wus_scatter_bucket')
        if not (_calls_of(gather_fn, src, 'all_gather') or
                _calls_of(gather_fn, src, 'hierarchical_all_gather')):
            findings.append(
                'plan.py: gather_updated_params no longer emits the '
                'bucketed param all-gather')
    static_src = re.sub(r'\s+', '',
                        ast.get_source_segment(src, static) or '')
    for token, what in (
            ("('psum_scatter','grad')",
             'grad-phase reduce-scatter'),
            ("('all_gather','param')",
             'param-phase all-gather'),
            ("'wus':True", 'wus tag')):
        if token not in static_src:
            findings.append(
                'plan.py: static_collective_schedule no longer emits '
                'the update-shard %s entry (%s) — the simulator would '
                'price a schedule without the update-sharding halves'
                % (what, token))
    for name, fn in (('sync_gradients', traced),
                     ('static_collective_schedule', static)):
        if not _calls_of(fn, src, 'pack_buckets'):
            findings.append('plan.py: %s no longer packs via '
                            'pack_buckets' % name)
    tso, sso = _sort_key(traced, src), _sort_key(static, src)
    if tso != sso:
        findings.append(
            'plan.py: bucket emission order DRIFTED — sync_gradients '
            'sorts by %r, static_collective_schedule by %r' % (tso,
                                                               sso))
    return findings


# -- reshard shape algebra ------------------------------------------------

def _layouts_for(shape, n):
    """Every layout an ExecutionPlan can place a var of ``shape`` in on
    an ``n``-way data axis, with the plan's padding rule."""
    outs = [{'sharded': False, 'axis': None, 'padded_dim': None,
             'pad': 0}]
    for axis, dim in enumerate(shape):
        if dim < n:
            continue   # the plan only shards axes >= n
        padded = -(-dim // n) * n
        outs.append({'sharded': True, 'axis': axis,
                     'padded_dim': padded, 'pad': padded - dim})
    return outs


def _holdings(layout, shape, n, d):
    """The logical flat-index set device ``d`` holds under ``layout``
    (pad rows excluded)."""
    import numpy as np
    idx = np.arange(int(np.prod(shape))).reshape(shape)
    if not layout['sharded']:
        return set(idx.ravel().tolist())
    ax, dim = layout['axis'], shape[layout['axis']]
    rows = layout['padded_dim'] // n
    lo, hi = d * rows, min((d + 1) * rows, dim)
    if lo >= dim:
        return set()
    sl = [slice(None)] * len(shape)
    sl[ax] = slice(lo, hi)
    return set(idx[tuple(sl)].ravel().tolist())


def _mock_plan(shape, layout, n):
    from types import SimpleNamespace
    import numpy as np
    var = SimpleNamespace(shape=tuple(shape), dtype=np.float32)
    vp = SimpleNamespace(var=var, state_sharded=layout['sharded'],
                         shard_axis=layout['axis'] or 0,
                         padded_dim=layout['padded_dim'],
                         pad=layout['pad'])
    return SimpleNamespace(var_plans={'v': vp}, num_replicas=n,
                           cost_params=None)


def check_reshard_algebra():
    """Element-preservation + op-kind preconditions over the sweep."""
    from autodist_tpu.parallel import reshard
    from autodist_tpu.simulator.cost_model import CostModelParams
    params = CostModelParams()
    findings = []
    shapes = [(8,), (8, 4), (9, 4), (8, 6), (6, 10)]
    for n in (2, 4):
        for shape in shapes:
            for src in _layouts_for(shape, n):
                for dst in _layouts_for(shape, n):
                    old = _mock_plan(shape, src, n)
                    new = _mock_plan(shape, dst, n)
                    ops = reshard.plan_reshard(old, new, params=params)
                    if len(ops) != 1:
                        findings.append(
                            'reshard: plan for %s n=%d covered %d ops '
                            'for 1 var' % (shape, n, len(ops)))
                        continue
                    op = ops[0]
                    ctx = 'reshard %s n=%d %s->%s (%s)' % (
                        shape, n, _fmt(src), _fmt(dst), op.kind)
                    findings.extend(_check_op(op, src, dst, shape, n,
                                              ctx))
    return findings


def _fmt(layout):
    if not layout['sharded']:
        return 'repl'
    return 'shard(ax%d,pad%d)' % (layout['axis'], layout['pad'])


def _check_op(op, src, dst, shape, n, ctx):
    problems = []
    # kind preconditions (the shape algebra each lowering requires)
    if op.kind == 'noop' and src != dst:
        problems.append('%s: noop chosen for a layout CHANGE' % ctx)
    if op.kind != 'noop' and src == dst:
        problems.append('%s: layout unchanged but op is not noop' % ctx)
    if op.kind == 'shard' and (src['sharded'] or not dst['sharded']):
        problems.append('%s: shard requires replicated->sharded' % ctx)
    if op.kind == 'all_gather' and (not src['sharded']
                                    or dst['sharded']):
        problems.append('%s: all_gather requires sharded->replicated'
                        % ctx)
    if op.kind == 'all_to_all':
        if not (src['sharded'] and dst['sharded']):
            problems.append('%s: all_to_all requires sharded->sharded'
                            % ctx)
        elif src['pad'] or dst['pad'] or src['axis'] == dst['axis']:
            problems.append(
                '%s: all_to_all chosen where its tiled split cannot '
                'lower (pad %d->%d, axis %s->%s)'
                % (ctx, src['pad'], dst['pad'], src['axis'],
                   dst['axis']))
    for layout, which in ((src, 'src'), (dst, 'dst')):
        if layout['sharded']:
            dim = shape[layout['axis']]
            if layout['padded_dim'] % n:
                problems.append('%s: %s padded_dim %d not divisible by '
                                'n=%d' % (ctx, which,
                                          layout['padded_dim'], n))
            if layout['padded_dim'] - layout['pad'] != dim:
                problems.append('%s: %s pad algebra broken (padded %d '
                                '- pad %d != dim %d)'
                                % (ctx, which, layout['padded_dim'],
                                   layout['pad'], dim))
    # element preservation: dst shards partition the logical set
    import numpy as np
    everything = set(range(int(np.prod(shape))))
    union, total = set(), 0
    for d in range(n):
        h = _holdings(dst, shape, n, d)
        union |= h
        total += len(h)
    if union != everything:
        problems.append('%s: destination layout LOSES elements (%d of '
                        '%d reachable)' % (ctx, len(union),
                                           len(everything)))
    if dst['sharded'] and total != len(everything):
        problems.append('%s: destination shards overlap (%d held vs '
                        '%d logical)' % (ctx, total, len(everything)))
    if op.kind in ('noop', 'shard') and op.wire_bytes:
        problems.append('%s: zero-wire kind claims %d wire bytes'
                        % (ctx, op.wire_bytes))
    if op.est_time_s < 0:
        problems.append('%s: negative cost estimate' % ctx)
    return problems


# -- absorbed wire-pricing drift check ------------------------------------

def check_wire_pricing():
    """Compressor registry vs cost_model._WIRE_ITEMSIZE (a compressor
    missing from the table silently prices as f32)."""
    from autodist_tpu.parallel.compressor import _REGISTRY
    from autodist_tpu.simulator.cost_model import _WIRE_ITEMSIZE
    registry, priced = set(_REGISTRY), set(_WIRE_ITEMSIZE)
    problems = []
    for name in sorted(registry - priced):
        problems.append('compressor registered but missing from '
                        'cost_model._WIRE_ITEMSIZE (would silently '
                        'price as f32): %s' % name)
    for name in sorted(priced - registry):
        problems.append('priced in cost_model._WIRE_ITEMSIZE but not '
                        'in the compressor registry (stale entry): %s'
                        % name)
    if not registry:
        problems.append('compressor registry is empty — the registry '
                        'moved or the import graph broke')
    return problems


def analyze():
    """Run all schedule/plan consistency checks. Returns finding
    strings (empty = clean)."""
    return (check_emission_predicates() + check_reshard_algebra() +
            check_wire_pricing())
