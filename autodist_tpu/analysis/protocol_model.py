"""Executable small-scope model of the control-plane protocol.

The costliest bugs in this system have been distributed-protocol
ORDERING races: PR 4's deleted-step-key resurrection (releasing a dead
worker's step counter by DELETE let any later delta-0 ``INCR`` read
recreate it at 0 and wedge every survivor's MINWAIT) and PR 6's
third-review admit inversion (publishing the adopted step floor BEFORE
the membership epoch bump left a mid-admit corpse's counter invisibly
frozen inside the gate's prefix-min, a permanent cohort stall). Both
were found by human review or chaos flakes; this module catches the
bug CLASS statically, in tier-1, by modeling the protocol small-scope
(2-3 workers, 2 steps, one crash) and letting
:mod:`~autodist_tpu.analysis.explore` enumerate every interleaving.

The model covers exactly the cross-process control-plane state the
native ``coord_service`` holds and the orderings ``runtime/session.py``
performs against it:

- counters with the service's real ``INCR`` semantics — including the
  load-bearing quirk that a delta-0 read CREATES a missing counter at 0
  (C++ ``map::operator[]``), the resurrection vector;
- per-connection writer fencing (``FENCE`` bind, mutation rejection
  once the fence counter passes the bound generation);
- ``publish_step`` as its real TWO RPCs — the delta-0 read and the
  relative-delta bump are separate transitions for every worker/joiner
  self-publish, so interleavings and crashes inside the publish window
  are explored (the exclusion RELEASE keeps both halves in one
  transition; :func:`svc_publish` documents why that is sound) — and
  the MINWAIT gate (>=k step counters under the prefix AND their
  min >= target);
- the exclude path (fence-everywhere -> atomic claim -> release ->
  epoch bump) with the release mode configurable
  (``sentinel``/HEAD vs ``delete``/pre-PR 4);
- the admit handshake (slot claim -> cap re-check -> fence bind ->
  floor scan -> epoch bump + floor publish) with the bump/publish
  order configurable (``epoch_first``/HEAD vs ``publish_first``/the
  pre-fix inversion) and the cap-race retirement togglable;
- membership visibility semantics: a survivor only refreshes its
  world/excluded view when it observes an epoch change, exactly like
  ``Session._check_peers_alive``.

What it deliberately does NOT model: tensor payloads, heartbeat
counters (ground-truth process status stands in for the
eventually-firing timeout — sound, because a crashed process's beat
counter never advances again), barriers, the purge/close protocol, and
real time. See ``docs/design/static-analysis.md`` for the extension
contract when a new protocol message is added.

Invariants (checked by :mod:`~autodist_tpu.analysis.explore`):

- **no fenced write commits** — once a fence-bound writer's exclusion
  claim is observable, none of its mutations may commit;
- **no deleted-counter resurrection** — a released worker's step
  counter must never be observed below the release sentinel again;
- **no invisible frozen counter** — from every reachable state, every
  live process can still finish (gate liveness); a stuck state's
  diagnosis names any step counter frozen in the prefix-min that no
  survivor's membership view contains;
- **cap-raced claims are retired** — a join claim that raced past
  ``AUTODIST_MAX_WORKERS`` ends excluded + sentinel-released, and live
  membership never exceeds the cap at rest.
"""
from dataclasses import dataclass, replace

#: The clean-close / exclusion release sentinel (coord_client
#: CLEAN_CLOSE_STEP): a published step at/above it is a RELEASE, not
#: training progress.
SENTINEL = 1 << 30


@dataclass(frozen=True)
class ProtocolConfig:
    """Orderings under test. The defaults are HEAD's (must explore
    clean); each historical bug is one field flipped back."""

    #: exclude-path release of the dead worker's step counter:
    #: 'sentinel' (HEAD) publishes CLEAN_CLOSE_STEP; 'delete' (the
    #: pre-PR 4 ordering) erases the key.
    release: str = 'sentinel'
    #: admit handshake tail: 'epoch_first' (HEAD) bumps the membership
    #: epoch before publishing the adopted floor; 'publish_first' is
    #: the inversion PR 6's third review fixed.
    admit_order: str = 'epoch_first'
    #: the exclude path's step order. HEAD fences the zombie on every
    #: service BEFORE the claim becomes observable.
    exclude_order: tuple = ('fence', 'claim', 'release', 'epoch')
    #: whether a join claim that raced past the cap retires its slot
    #: (excluded marker + sentinel release) before refusing.
    retire_on_cap_race: bool = True
    #: training steps per worker (small scope).
    steps: int = 2
    #: staleness window of the MINWAIT gate.
    staleness: int = 0
    #: AUTODIST_MAX_WORKERS for the cap-race scenario.
    max_workers: int = 3


HEAD = ProtocolConfig()
#: PR 4's historical bug: exclusion released the dead step key by
#: DELETE; any later delta-0 INCR read resurrects it at 0.
PR4_RESURRECTION = replace(HEAD, release='delete')
#: PR 6's historical bug: the admit handshake published the adopted
#: floor before the epoch bump.
PR6_ADMIT_INVERSION = replace(HEAD, admit_order='publish_first')
#: Extra seeded orderings (not historical, but the same class): the
#: exclusion claim observable before the zombie is fenced...
UNFENCED_EXCLUDE = replace(HEAD,
                           exclude_order=('claim', 'fence', 'release',
                                          'epoch'))
#: ...and a cap-raced join slot abandoned instead of retired.
UNRETIRED_CAP_RACE = replace(HEAD, retire_on_cap_race=False)


class Scenario:
    """One bounded system to explore: an initial model state plus the
    crash/stall choices the explorer may inject and an optional
    ``terminal_check(model) -> [(kind, msg)]`` terminal invariant.

    The explorer (:mod:`~autodist_tpu.analysis.explore`) is model-
    agnostic as long as the state dict keeps the shared shape
    (``counters``/``kv``/``procs``/``slot_owner``/``crash_budget``/
    ``violation`` with hashable values); which model a scenario speaks
    is carried by three hooks:

    - ``transitions_fn(model, cfg, proc) -> [(actor, label, fn)]`` —
      the per-process transition generator (defaults to this module's
      :func:`proc_transitions`);
    - ``on_crash(model, proc)`` — side effects of an injected crash
      beyond ``status='crashed'`` (the data-plane model uses it for
      the service's disconnect-time ``SeqAborter``);
    - ``describe_stuck(model) -> str`` — the stall diagnosis (defaults
      to the control-plane one, which names invisible frozen step
      counters in the gate prefix-min).
    """

    def __init__(self, name, cfg, model, crashable=(), stallable=(),
                 terminal_check=None, transitions_fn=None,
                 on_crash=None, describe_stuck=None):
        self.name = name
        self.cfg = cfg
        self.model = model
        self.crashable = tuple(crashable)
        self.stallable = tuple(stallable)
        self.terminal_check = terminal_check
        self.transitions_fn = transitions_fn or proc_transitions
        self.on_crash = on_crash
        self.describe_stuck = describe_stuck


# -- service semantics ----------------------------------------------------

def _set_violation(m, kind, msg):
    if m['violation'] is None:
        m['violation'] = (kind, msg)


def _check_resurrection(m, key):
    """A released worker's step counter observed below the sentinel is
    the PR 4 bug re-derived."""
    w = key[len('step/'):]
    if m['kv'].get('released/' + w) and m['counters'][key] < SENTINEL:
        _set_violation(
            m, 'resurrection',
            'released step counter %s recreated at %d (< sentinel): a '
            'delta-0 INCR read resurrected the deleted key — every '
            "survivor's MINWAIT prefix-min is now wedged at it"
            % (key, m['counters'][key]))


def _mutate_ok(m, proc):
    """The service's fence check for one mutating frame by ``proc``,
    plus the fenced-write-commit invariant: a fence-BOUND writer whose
    exclusion claim is already observable must never commit."""
    p = m['procs'][proc]
    fk = p.get('fence_key')
    if fk and m['counters'].get(fk, 0) > p.get('fence_gen', 0):
        # ERR fenced; the session surfaces FencedWriteError and dies
        p['status'] = 'failed'
        return False
    wkey = p.get('wkey')
    if fk and wkey and m['counters'].get('excluded/' + wkey, 0) > 0:
        _set_violation(
            m, 'fenced-write-commit',
            'a mutation by %s COMMITTED after its exclusion claim was '
            'observable — the exclude path must fence the zombie on '
            'every service before the claim lands' % proc)
    return True


def svc_incr(m, proc, key, delta):
    """INCR: atomic add, fence-checked only when delta != 0 — and the
    delta-0 read CREATES a missing counter at 0, exactly like the
    service's ``map::operator[]``. Returns the value, or None on ERR
    fenced."""
    if delta and not _mutate_ok(m, proc):
        return None
    v = m['counters'].get(key, 0) + delta
    m['counters'][key] = v
    if key.startswith('step/'):
        _check_resurrection(m, key)
    return v


def svc_delete(m, proc, key):
    """DEL (fence-checked like every mutation)."""
    if not _mutate_ok(m, proc):
        return False
    m['counters'].pop(key, None)
    return True


def svc_step_read(m, proc, wkey):
    """The read half of ``publish_step``: a delta-0 INCR — creates a
    missing counter at 0."""
    return svc_incr(m, proc, 'step/' + wkey, 0)


def svc_step_bump(m, proc, wkey, target, cur):
    """The bump half of ``publish_step``: a RELATIVE-delta INCR
    computed from the earlier read (``incr(key, target - cur)``), so a
    concurrent write landing between the two RPCs composes additively
    — exactly the service's semantics."""
    if target <= cur:
        return True
    return svc_incr(m, proc, 'step/' + wkey, target - cur) is not None


def svc_publish(m, proc, wkey, step):
    """``publish_step`` as ONE transition (both RPCs). Used only for
    the exclusion/retirement RELEASE, whose writers are not crashable
    in any scenario; keeping it atomic is sound for the sentinel
    because step counters are monotone under publishes, so the
    relative bump ``cur' + (SENTINEL - cur)`` with ``cur' >= cur``
    never lands below the sentinel. Worker/joiner self-publishes go
    through the split :func:`svc_step_read`/:func:`svc_step_bump`
    transitions instead, so the intra-publish window IS explored."""
    cur = svc_step_read(m, proc, wkey)
    if cur is None:
        return False
    return svc_step_bump(m, proc, wkey, step, cur)


def gate_ready(m, p, target):
    """MINWAIT over the step/ prefix: >= k counters AND min >= target,
    with k = the party count from THIS process's membership view
    (world_seen minus its excluded set), like the session's callable
    ``num_workers``."""
    k = p['world_seen'] - len(p['excluded'])
    steps = [v for key, v in m['counters'].items()
             if key.startswith('step/')]
    return len(steps) >= k and (min(steps) if steps else 0) >= target


def _refresh(m, p):
    """Session._refresh_membership: adopt the plane's world + excluded
    set (only ever called after observing an epoch change)."""
    p['epoch_seen'] = m['counters'].get('epoch', 0)
    p['world_seen'] = max(p['world_seen'],
                          m['counters'].get('join/world', 0))
    p['excluded'] = tuple(sorted(
        'p%d' % i for i in range(p['world_seen'])
        if m['counters'].get('excluded/p%d' % i, 0) > 0))


def _detectable_dead(m, p):
    """Members of THIS process's view whose ground-truth process is
    crashed/stalled/failed — the abstraction of 'heartbeat stalled past
    the timeout' (a dead process's beat counter never advances again,
    so the timeout eventually fires; a stalled one may be declared dead
    falsely, which is exactly the zombie case fencing must survive)."""
    out = []
    for i in range(p['world_seen']):
        w = 'p%d' % i
        if w == p.get('wkey') or w in p['excluded']:
            continue
        owner = m['slot_owner'].get(w)
        if owner is None:
            continue
        if m['procs'][owner]['status'] in ('crashed', 'stalled',
                                           'failed'):
            out.append(w)
    return out


# -- process roles --------------------------------------------------------

def _worker_transitions(m, cfg, n, p):
    ts = []
    if p['mode'] == 'excl':
        w = p['excl_target']
        stepname = cfg.exclude_order[p['excl_i']]

        def excl(m2, stepname=stepname, w=w, n=n):
            p2 = m2['procs'][n]
            if stepname == 'fence':
                svc_incr(m2, n, 'fence/' + w, 1)
            elif stepname == 'claim':
                v = svc_incr(m2, n, 'excluded/' + w, 1)
                p2['excl_won'] = (v == 1)
            elif stepname == 'release':
                if p2['excl_won']:
                    if cfg.release == 'delete':
                        svc_delete(m2, n, 'step/' + w)
                    else:
                        svc_publish(m2, n, w, SENTINEL)
                    m2['kv']['released/' + w] = '1'
            elif stepname == 'epoch':
                if p2['excl_won']:
                    svc_incr(m2, n, 'epoch', 1)
                _refresh(m2, p2)
            if p2['status'] == 'running':
                p2['excl_i'] += 1
                if p2['excl_i'] >= len(cfg.exclude_order):
                    p2['mode'] = 'run'
                    p2['excl_i'] = 0

        ts.append((n, 'exclude[%s] %s' % (stepname, w), excl))
        return ts

    if p['step'] > cfg.steps:
        def finish(m2, n=n):
            m2['procs'][n]['status'] = 'done'
        ts.append((n, 'finish (clean close)', finish))
        return ts

    if p['phase'] == 'push':
        def push(m2, n=n):
            p2 = m2['procs'][n]
            if svc_incr(m2, n, 'data/shared', 1) is not None:
                p2['phase'] = 'publish'
        ts.append((n, 'push delta (step %d)' % p['step'], push))
        return ts

    if p['phase'] == 'publish':
        def pub_read(m2, n=n):
            p2 = m2['procs'][n]
            p2['pub_cur'] = svc_step_read(m2, n, p2['wkey'])
            p2['phase'] = 'publish2'
        ts.append((n, 'publish step %d: read own counter (delta-0 '
                   'INCR)' % p['step'], pub_read))
        return ts

    if p['phase'] == 'publish2':
        def pub_bump(m2, n=n):
            p2 = m2['procs'][n]
            if svc_step_bump(m2, n, p2['wkey'], p2['step'],
                             p2['pub_cur']):
                p2['phase'] = 'gate'
        ts.append((n, 'publish step %d: bump (relative INCR)'
                   % p['step'], pub_bump))
        return ts

    # phase == 'gate': pass when MINWAIT is satisfied; otherwise the
    # failure-check alternatives (adopt an epoch change; declare a dead
    # member and enter the exclude path) are the only way forward —
    # exactly the staleness_gate slice loop.
    target = p['step'] - cfg.staleness
    if target <= 0 or gate_ready(m, p, target):
        def gate_pass(m2, n=n):
            p2 = m2['procs'][n]
            p2['step'] += 1
            p2['phase'] = 'push' if p2['pusher'] else 'publish'
        ts.append((n, 'gate passes (step %d)' % p['step'], gate_pass))
    if m['counters'].get('epoch', 0) != p['epoch_seen']:
        def adopt(m2, n=n):
            _refresh(m2, m2['procs'][n])
        ts.append((n, 'adopt epoch change (refresh membership)', adopt))
    if p['excluder']:
        for w in _detectable_dead(m, p):
            def declare(m2, n=n, w=w):
                p2 = m2['procs'][n]
                p2['mode'] = 'excl'
                p2['excl_i'] = 0
                p2['excl_target'] = w
                p2['excl_won'] = False
            ts.append((n, 'declare %s dead (heartbeat timeout)' % w,
                       declare))
    return ts


def _joiner_transitions(m, cfg, n, p):
    jpc = p['jpc']
    if jpc == 0:
        def precheck(m2, n=n):
            p2 = m2['procs'][n]
            world = m2['counters'].get('join/world', 0)
            excl = sum(1 for i in range(world)
                       if m2['counters'].get('excluded/p%d' % i, 0) > 0)
            if world - excl >= cfg.max_workers:
                p2['status'] = 'failed'   # refused before any claim
                p2['refused'] = 'precheck'
            else:
                p2['jpc'] = 1
        return [(n, 'admit: pre-check live membership vs cap',
                 precheck)]
    if jpc == 1:
        def claim(m2, n=n):
            p2 = m2['procs'][n]
            world = svc_incr(m2, n, 'join/world', 1)
            p2['ordinal'] = world - 1
            p2['wkey'] = 'p%d' % p2['ordinal']
            m2['slot_owner'][p2['wkey']] = n
            p2['jpc'] = 2
        return [(n, 'admit: claim slot (INCR join/world)', claim)]
    if jpc == 2:
        def postcheck(m2, n=n):
            p2 = m2['procs'][n]
            world = m2['counters'].get('join/world', 0)
            excl = sum(1 for i in range(world)
                       if m2['counters'].get('excluded/p%d' % i, 0) > 0)
            if world - excl > cfg.max_workers:
                p2['refused'] = 'raced'
                if cfg.retire_on_cap_race:
                    p2['jpc'] = 20
                else:
                    p2['status'] = 'failed'   # slot abandoned un-retired
            else:
                p2['jpc'] = 3
        return [(n, 'admit: re-check cap after claim', postcheck)]
    if jpc == 20:
        def retire_mark(m2, n=n):
            p2 = m2['procs'][n]
            svc_incr(m2, n, 'excluded/' + p2['wkey'], 1)
            p2['jpc'] = 21
        return [(n, 'admit: retire raced slot (excluded marker)',
                 retire_mark)]
    if jpc == 21:
        def retire_release(m2, n=n):
            p2 = m2['procs'][n]
            svc_publish(m2, n, p2['wkey'], SENTINEL)
            m2['kv']['released/' + p2['wkey']] = '1'
            p2['status'] = 'failed'
        return [(n, 'admit: retire raced slot (sentinel release)',
                 retire_release)]
    if jpc == 3:
        def gen_read(m2, n=n):
            p2 = m2['procs'][n]
            p2['fence_gen'] = svc_incr(m2, n, 'fence/' + p2['wkey'],
                                       0)
            p2['jpc'] = 30
        return [(n, 'admit: read own fence generation', gen_read)]
    if jpc == 30:
        # the two-RPC bind window: a fence bump landing between the
        # generation read and the FENCE bind is rejected at bind time
        def bind(m2, n=n):
            p2 = m2['procs'][n]
            key = 'fence/' + p2['wkey']
            if m2['counters'].get(key, 0) > p2['fence_gen']:
                p2['status'] = 'failed'   # superseded before binding
                return
            p2['fence_key'] = key
            p2['jpc'] = 4
        return [(n, 'admit: bind fence generation', bind)]
    if jpc == 4:
        if p['scan_i'] < p['ordinal']:
            def floor_read(m2, n=n):
                p2 = m2['procs'][n]
                # the delta-0 INCR read — creates missing counters
                step = svc_incr(m2, n, 'step/p%d' % p2['scan_i'], 0)
                if step != 0 and step < SENTINEL and \
                        (p2['floor'] == 0 or step < p2['floor']):
                    p2['floor'] = step
                p2['scan_i'] += 1
            return [(n, "admit: scan step/p%d for the floor "
                     '(delta-0 INCR)' % p['scan_i'], floor_read)]
        def scan_done(m2, n=n):
            m2['procs'][n]['jpc'] = 5
        return [(n, 'admit: adopt step floor', scan_done)]
    tail = (('epoch', 'pub_read', 'pub_bump')
            if cfg.admit_order == 'epoch_first'
            else ('pub_read', 'pub_bump', 'epoch'))
    if jpc in (5, 6, 7):
        stepname = tail[jpc - 5]

        def admit_tail(m2, stepname=stepname, n=n):
            p2 = m2['procs'][n]
            if stepname == 'epoch':
                if svc_incr(m2, n, 'epoch', 1) is None:
                    return
                _refresh(m2, p2)
            elif stepname == 'pub_read':
                p2['pub_cur'] = svc_step_read(m2, n, p2['wkey'])
            else:
                if not svc_step_bump(m2, n, p2['wkey'], p2['floor'],
                                     p2['pub_cur']):
                    return
            p2['jpc'] += 1
            if p2['jpc'] == 8:
                p2['pub'] = p2['floor']
        label = {'epoch': 'admit: bump membership epoch',
                 'pub_read': 'admit: publish adopted step floor '
                             '(read half)',
                 'pub_bump': 'admit: publish adopted step floor'}[
                     stepname]
        return [(n, label, admit_tail)]
    # admitted: train (publish only — enough to un-block cohort
    # gates), through the same split read/bump publish
    if p['pub'] < cfg.steps:
        if p['train_phase'] == 'read':
            def train_read(m2, n=n):
                p2 = m2['procs'][n]
                p2['pub_cur'] = svc_step_read(m2, n, p2['wkey'])
                p2['train_phase'] = 'bump'
            return [(n, 'publish step %d (post-admit): read'
                     % (p['pub'] + 1), train_read)]

        def train_bump(m2, n=n):
            p2 = m2['procs'][n]
            if svc_step_bump(m2, n, p2['wkey'], p2['pub'] + 1,
                             p2['pub_cur']):
                p2['pub'] += 1
                p2['train_phase'] = 'read'
        return [(n, 'publish step %d (post-admit): bump'
                 % (p['pub'] + 1), train_bump)]

    def jdone(m2, n=n):
        m2['procs'][n]['status'] = 'done'
    return [(n, 'finish (clean close)', jdone)]


def _monitor_transitions(m, cfg, n, p):
    targets = p['targets'].split(',')
    if p['mpc'] >= len(targets):
        def mdone(m2, n=n):
            m2['procs'][n]['status'] = 'done'
        return [(n, 'monitor done', mdone)]
    w = targets[p['mpc']]

    def poll(m2, n=n, w=w):
        # external monitors and the admit floor scan both read step
        # counters through the delta-0 INCR idiom — THE read that
        # resurrects a deleted key
        svc_incr(m2, n, 'step/' + w, 0)
        m2['procs'][n]['mpc'] += 1
    return [(n, 'monitor polls step/%s (delta-0 INCR)' % w, poll)]


def proc_transitions(m, cfg, n):
    p = m['procs'][n]
    if p['status'] != 'running':
        return []
    role = p['role']
    if role == 'worker':
        return _worker_transitions(m, cfg, n, p)
    if role == 'joiner':
        return _joiner_transitions(m, cfg, n, p)
    return _monitor_transitions(m, cfg, n, p)


# -- scenario construction ------------------------------------------------

def _worker(n, world, pusher=False, excluder=True):
    return {'role': 'worker', 'status': 'running', 'step': 1,
            'phase': 'push' if pusher else 'publish', 'mode': 'run',
            'excl_i': 0, 'excl_target': '', 'excl_won': False,
            'pub_cur': 0, 'epoch_seen': 0, 'world_seen': world,
            'excluded': (), 'fence_key': 'fence/' + n, 'fence_gen': 0,
            'wkey': n, 'pusher': pusher, 'excluder': excluder,
            'stall_budget': 0}


def _joiner(n):
    return {'role': 'joiner', 'status': 'running', 'jpc': 0,
            'ordinal': -1, 'wkey': '', 'floor': 0, 'scan_i': 0,
            'pub': 0, 'pub_cur': 0, 'train_phase': 'read',
            'refused': '', 'fence_key': '', 'fence_gen': 0,
            'epoch_seen': 0, 'world_seen': 0, 'excluded': (),
            'stall_budget': 0}


def _monitor(n, targets):
    return {'role': 'monitor', 'status': 'running', 'mpc': 0,
            'targets': ','.join(targets), 'stall_budget': 0}


def _base_model(procs, world, crash_budget=0):
    return {'counters': {'join/world': world, 'epoch': 0},
            'kv': {'init-done': '1'},
            'procs': procs,
            'slot_owner': {n: n for n, p in procs.items()
                           if p['role'] == 'worker'},
            'crash_budget': crash_budget,
            'violation': None}


def exclude_scenario(cfg):
    """Three launch workers; one may crash at any point; the survivors
    run the exclude path; an external monitor polls step counters
    (delta-0 INCR) at arbitrary interleavings. PR 4's delete-release
    must resurface as a resurrection counterexample here."""
    procs = {'p0': _worker('p0', 3), 'p1': _worker('p1', 3),
             'p2': _worker('p2', 3, excluder=False),
             'mon': _monitor('mon', ('p0', 'p1', 'p2'))}
    return Scenario('exclude', cfg, _base_model(procs, 3,
                                                crash_budget=1),
                    crashable=('p2',))


def admit_scenario(cfg):
    """Two launch workers training through gates; one joiner runs the
    admit handshake and may crash between ANY two of its steps. PR 6's
    publish-before-epoch inversion must resurface as a stall (the
    invisible frozen counter) here."""
    procs = {'p0': _worker('p0', 2), 'p1': _worker('p1', 2),
             'j': _joiner('j')}
    return Scenario('admit', cfg, _base_model(procs, 2,
                                              crash_budget=1),
                    crashable=('j',))


def zombie_scenario(cfg):
    """A worker stalls mid-step, gets declared dead and excluded, then
    resumes and tries to keep writing. With HEAD's fence-before-claim
    order every resumed write is rejected; the flipped order lets one
    commit after the exclusion is observable."""
    procs = {'p0': _worker('p0', 2),
             'p1': _worker('p1', 2, pusher=True, excluder=False)}
    return Scenario('zombie', cfg, _base_model(procs, 2),
                    stallable=('p1',))


def _cap_terminal_check(m, max_workers):
    problems = []
    world = m['counters'].get('join/world', 0)
    excl = sum(1 for i in range(world)
               if m['counters'].get('excluded/p%d' % i, 0) > 0)
    if world - excl > max_workers:
        problems.append((
            'cap-exceeded',
            'live membership %d exceeds AUTODIST_MAX_WORKERS=%d at '
            'rest' % (world - excl, max_workers)))
    for n, p in m['procs'].items():
        if p['role'] != 'joiner' or p.get('refused') != 'raced':
            continue
        w = p['wkey']
        if m['counters'].get('excluded/' + w, 0) <= 0 or \
                m['counters'].get('step/' + w, 0) < SENTINEL:
            problems.append((
                'cap-slot-unretired',
                'join claim %s raced past the cap but was not retired '
                '(excluded marker + sentinel release): survivors must '
                'pay a heartbeat window to skip it' % w))
    return problems


def cap_race_scenario(cfg):
    """Two concurrent joiners race one slot of cap headroom: both pass
    the pre-check, both claim, the loser must retire its slot."""
    procs = {'p0': _worker('p0', 2), 'p1': _worker('p1', 2),
             'j2': _joiner('j2'), 'j3': _joiner('j3')}
    # the launch cohort is already done training: the scenario isolates
    # the claim race (workers keep their published step on the plane)
    for n in ('p0', 'p1'):
        procs[n]['status'] = 'done'
    model = _base_model(procs, 2)
    model['counters']['step/p0'] = cfg.steps
    model['counters']['step/p1'] = cfg.steps
    return Scenario(
        'cap_race', cfg, model,
        terminal_check=lambda m: _cap_terminal_check(m,
                                                     cfg.max_workers))


def scenarios(cfg):
    """The standard scenario suite for one configuration."""
    return [exclude_scenario(cfg), admit_scenario(cfg),
            zombie_scenario(cfg), cap_race_scenario(cfg)]
