"""Static analysis over the distributed runtime — tier-1 correctness
backstops that run with no devices and no processes.

Six analyzers, one CLI (``tools/analyze.py``):

- :mod:`~autodist_tpu.analysis.protocol_model` +
  :mod:`~autodist_tpu.analysis.explore` — an executable small-scope
  model of the control-plane protocol (fence generations, the exclude
  path, the admit handshake, publish/MINWAIT gate semantics) explored
  exhaustively over bounded interleavings with crashes. The two
  costliest historical bugs (PR 4's deleted-step-key resurrection,
  PR 6's admit-ordering inversion) re-derive as counterexample traces
  when the model is flipped to the pre-fix orderings; HEAD's orderings
  explore clean.
- :mod:`~autodist_tpu.analysis.data_plane_model` — the same treatment
  for the PS **data plane**: chunked write sequences + torn-read
  version parity, the disconnect-time sequence abort, the
  under-tensor-lock fence re-check, the depth-2 pipeline's prefetch
  peer-floor guard, and the telemetry batch-counter/cursor protocol.
  Three more historical bugs (PR 1's offset-0 abort, PR 5's
  disconnect wedge, PR 11's cursor race) re-derive as counterexample
  traces.
- :mod:`~autodist_tpu.analysis.epoch_swap_model` — the PROSPECTIVE
  strategy-distribution-epoch handshake (ROADMAP 2), verified before
  it ships: the stage → ack-quorum → boundary-arm → swap-at-boundary
  ordering explores clean, and the tempting-but-wrong orderings
  (swap-before-ack-quorum, naive chief-step boundary)
  counterexample. The clean ordering is the implementation contract
  in ``docs/design/static-analysis.md``.
- :mod:`~autodist_tpu.analysis.fence_lint` — parses the native
  ``coord_service.cc`` dispatcher and proves every mutating command is
  fence-checked (with the under-tensor-lock re-check for ``B*``
  commands), every size-declaring command bounds its declared
  allocation against ``kMaxPayload`` before allocating, and the
  header stays in sync; absorbs ``tools/check_protocol.py``.
- :mod:`~autodist_tpu.analysis.env_lint` — every ``AUTODIST_*`` env
  read in the tree must be declared in ``const.py``'s ENV registry,
  every worker-affecting knob must ride the coordinator's forwarding
  set (or carry an explicit exemption reason), and every knob must be
  documented under ``docs/`` with choice sets in sync.
- :mod:`~autodist_tpu.analysis.schedule_lint` — cross-checks
  ``plan.sync_gradients``'s emission predicates against
  ``static_collective_schedule`` at the AST level, verifies
  ``reshard.plan_reshard`` layout moves are element-preserving by
  shape algebra, and absorbs the wire-pricing drift check.

Every analyzer returns a list of finding strings (empty = clean) so
``tools/analyze.py --all`` can aggregate them into one exit code and an
optional ``--json`` report. Design notes and the extension contract
(required reading before adding a protocol message — ROADMAP 3a):
``docs/design/static-analysis.md``.
"""
