"""``python -m autodist_tpu.launch`` — multi-host process launcher.

See :func:`autodist_tpu.runtime.coordinator.launch_cli`.
"""
import sys

from autodist_tpu.runtime.coordinator import launch_cli

if __name__ == '__main__':
    sys.exit(launch_cli())
