"""Functional training API: the big-model path.

The reference's session path captures an unmodified TF-graph program and
rewrites it (SURVEY.md §3.2). For models written against the functional
module system (:mod:`autodist_tpu.models`), the TPU-native path skips
capture entirely: the user hands a model + optimizer + :class:`ParallelSpec`
to :class:`Trainer`, which

1. builds the device mesh (data/pipe/seq/expert/model axes),
2. binds every param to a ``NamedSharding`` from its logical axes
   (ZeRO stages extend the binding over the data axis),
3. compiles ONE fused XLA train step — forward, backward, collectives,
   optimizer — via ``jit`` with explicit in/out shardings and donated
   state (GSPMD inserts the DP/TP/EP collectives; sequence parallelism
   runs the model inside a partial-manual ``shard_map`` for ring
   attention),
4. exposes reference-shaped ergonomics: ``init`` / ``step`` / fetch.

This is the lowering target the strategy builders compile to for
functional models (strategy → ParallelSpec adapter in
:mod:`autodist_tpu.strategy.adapter`).
"""
import functools
from dataclasses import dataclass
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from autodist_tpu.const import AXIS_DATA, AXIS_PIPELINE, AXIS_SEQUENCE
from autodist_tpu.parallel.axes import (ParallelSpec, sharding_ctx,
                                        shardings_for_tree, spec_for_axes)
from autodist_tpu.utils import logging


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: Any

    @classmethod
    def create(cls, params, opt_state):
        return cls(params=params, opt_state=opt_state,
                   step=jnp.zeros((), jnp.int32))


class Trainer:
    """Compile + drive distributed training of a functional model.

    Args:
        model: a :class:`autodist_tpu.models.core.Module` with
            ``init``/``apply``/``axes`` (and ``loss`` unless ``loss_fn``
            is given).
        optimizer: an optax ``GradientTransformation``.
        spec: :class:`ParallelSpec`; defaults to pure DP over all devices.
        loss_fn: ``loss_fn(params, batch) -> scalar``; defaults to
            ``model.loss``. In sequence-parallel mode the model must
            provide ``per_token_loss`` instead.
        mesh: optional prebuilt mesh (else ``spec.build_mesh()``).
    """

    def __init__(self, model, optimizer, spec=None, loss_fn=None,
                 mesh=None, rules=None, donate=True):
        self.model = model
        self.optimizer = optimizer
        self.spec = spec or ParallelSpec()
        self.mesh = mesh if mesh is not None else self.spec.build_mesh()
        self.rules = rules if rules is not None else self.spec.rules
        self._loss_fn = loss_fn
        self._donate = donate
        self._axes_tree = model.axes()
        self.param_shardings = shardings_for_tree(
            self._axes_tree, self.rules, self.mesh)
        self._step_cache = {}
        # model state (BatchNorm running stats): non-trainable leaves
        # advance via recorded updates, not the optimizer
        self._has_state = getattr(model, 'has_state', lambda: False)()
        if self._has_state:
            from autodist_tpu.models.core import assign_state_paths
            assign_state_paths(model)
            self._trainable_mask = model.trainable_mask()
            self._state_paths = [
                tuple(str(k.key) for k in path)
                for path, leaf in jax.tree_util.tree_flatten_with_path(
                    self._trainable_mask)[0] if not leaf]
        logging.info('Trainer mesh: %s, zero=%d, sp=%d',
                     dict(self.mesh.shape), self.spec.zero, self.spec.sp)

    # -- sharding helpers --------------------------------------------------
    def _zero_extend(self, sharding, shape):
        """Extend a sharding over the data axis on the first free
        divisible dim (ZeRO/FSDP-style). Used for optimizer slots
        (zero>=2) and params (zero==3)."""
        spec = list(sharding.spec) + [None] * (len(shape) -
                                               len(sharding.spec))
        dp = self.mesh.shape[AXIS_DATA]
        if dp <= 1:
            return sharding
        used = {a for a in spec if a is not None}
        if AXIS_DATA in used:
            return sharding
        for i, dim in enumerate(shape):
            if spec[i] is None and dim % dp == 0 and dim >= dp:
                spec[i] = AXIS_DATA
                return NamedSharding(self.mesh, P(*spec))
        return sharding

    def _param_sharding_tree(self, params):
        shardings = self.param_shardings
        if self.spec.zero >= 3:
            shardings = jax.tree.map(
                lambda s, p: self._zero_extend(s, p.shape),
                shardings, params)
        return shardings

    def _opt_sharding(self, opt_state, params, param_shardings):
        """Shard optimizer slots structurally: optax state trees mirror the
        param treedef (Adam's mu/nu etc.), so any subtree of ``opt_state``
        whose structure equals the params' is given the corresponding
        param's sharding leaf-for-leaf — no shape-collision ambiguity.
        Leaves outside such subtrees (step counters, scalars) replicate."""
        param_def = jax.tree.structure(params)
        flat_params = jax.tree.leaves(params)
        flat_shards = jax.tree.leaves(
            param_shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
        replicated = NamedSharding(self.mesh, P())
        # Fallbacks for leaves inside states that do not mirror the param
        # treedef exactly (optax.masked / multi_transform insert
        # placeholder nodes): first match the leaf's tree PATH against a
        # param path suffix (state trees nest the param tree under
        # wrapper keys like inner_state/mu, so param names survive in the
        # path); only then fall back to shape — and NEVER guess between
        # same-shape params with different shardings: ambiguous shapes
        # replicate (correct via resharding, predictable placement).
        pp = jax.tree_util.tree_flatten_with_path(params)[0]
        param_paths = []
        for (path, p), s in zip(pp, flat_shards):
            keys = tuple(str(getattr(k, 'key', getattr(k, 'idx', k)))
                         for k in path)
            param_paths.append((keys, tuple(p.shape), s))
        by_shape = {}
        for p, s in zip(flat_params, flat_shards):
            by_shape.setdefault(tuple(p.shape), set()).add(s)

        def mirrors_params(node):
            try:
                return jax.tree.structure(node) == param_def
            except Exception:
                return False

        def place_leaf(path_keys, node):
            shape = tuple(getattr(node, 'shape', ()))
            # path-suffix match: unique param whose full path ends the
            # state leaf's path (and whose shape agrees)
            cands = [s for keys, pshape, s in param_paths
                     if pshape == shape and len(path_keys) >= len(keys)
                     and path_keys[-len(keys):] == keys]
            if len(set(cands)) == 1:
                sh = cands[0]
            else:
                shs = by_shape.get(shape, set())
                if len(shs) != 1:
                    if len(shs) > 1:
                        logging.debug(
                            'optimizer leaf %s: shape %s matches params '
                            'with differing shardings; replicating',
                            '/'.join(path_keys), shape)
                    return replicated
                sh = next(iter(shs))
            if self.spec.zero >= 2:
                return self._zero_extend(sh, node.shape)
            return sh

        def place(path, node):
            if mirrors_params(node):
                leaves = jax.tree.leaves(node)
                placed = []
                for leaf, p, sh in zip(leaves, flat_params, flat_shards):
                    if tuple(getattr(leaf, 'shape', ())) != tuple(p.shape):
                        placed.append(replicated)  # e.g. scalar count
                    elif self.spec.zero >= 2:
                        placed.append(self._zero_extend(sh, leaf.shape))
                    else:
                        placed.append(sh)
                return jax.tree.unflatten(param_def, placed)
            keys = tuple(str(getattr(k, 'key', getattr(k, 'idx', k)))
                         for k in path)
            return place_leaf(keys, node)

        return jax.tree_util.tree_map_with_path(
            place, opt_state, is_leaf=mirrors_params)

    def batch_sharding(self, batch):
        """Leading dim over data; dim 1 over seq for rank>=2 leaves when
        sequence parallelism is on."""
        def leaf_sharding(x):
            nd = getattr(x, 'ndim', 0)
            if nd == 0:
                return NamedSharding(self.mesh, P())
            if nd >= 2 and self.spec.sp > 1:
                return NamedSharding(self.mesh, P(AXIS_DATA, AXIS_SEQUENCE))
            return NamedSharding(self.mesh, P(AXIS_DATA))
        return jax.tree.map(leaf_sharding, batch)

    def shard_batch(self, batch):
        """Host batch -> sharded device arrays (remapper feed equivalent)."""
        return jax.tree.map(
            lambda x, s: jax.device_put(jnp.asarray(x), s),
            batch, self.batch_sharding(batch))

    # -- init --------------------------------------------------------------
    def init(self, rng, params=None):
        """Materialize sharded TrainState (params + optimizer slots)."""
        if params is None:
            with sharding_ctx(self.mesh, self.rules):
                shapes = jax.eval_shape(self.model.init, rng)
                shardings = self._param_sharding_tree(shapes)
                init_fn = jax.jit(self.model.init,
                                  out_shardings=shardings)
                params = init_fn(rng)
        else:
            params = jax.tree.map(
                lambda x, s: jax.device_put(jnp.asarray(x), s),
                params, self._param_sharding_tree(params))
        opt_state = jax.jit(self.optimizer.init)(params)
        opt_shardings = self._opt_sharding(opt_state, params,
                                           self._param_sharding_tree(params))
        opt_state = jax.tree.map(
            lambda x, s: jax.device_put(x, s), opt_state, opt_shardings)
        return TrainState.create(params, opt_state)

    # -- the compiled step -------------------------------------------------
    @property
    def manual_axes(self):
        """Mesh axes the step runs manually (inside shard_map): pipeline
        (GPipe ppermute schedule) and sequence (ring attention)."""
        axes = []
        if self.spec.pp > 1:
            axes.append(AXIS_PIPELINE)
        if self.spec.sp > 1:
            axes.append(AXIS_SEQUENCE)
        return tuple(axes)

    def loss_for(self, params, batch):
        if self.manual_axes:
            return self._manual_loss(params, batch)
        if self._loss_fn is not None:
            return self._loss_fn(params, batch)
        return self.model.loss(params, batch)

    def _manual_spec(self, axes):
        """A param's in_spec for the manual region: its full spec with
        non-manual (still-automatic) mesh axes stripped."""
        full = spec_for_axes(axes, self.rules, self.mesh)
        manual = self.manual_axes
        kept = [a if a in manual else None for a in full]
        while kept and kept[-1] is None:
            kept.pop()
        return P(*kept)

    def _manual_loss(self, params, batch):
        """Sequence/pipeline-parallel loss: the model runs inside a
        partial-manual shard_map (ring attention over ``seq``, GPipe over
        ``pipe``); per-token losses reduce outside."""
        model = self.model
        rules = self.rules
        mesh = self.mesh
        manual = self.manual_axes
        options = {'microbatches': self.spec.microbatches,
                   'pp_schedule': getattr(self.spec, 'pp_schedule',
                                          'gpipe'),
                   'pp_variant': getattr(self.spec, 'pp_variant',
                                         'auto'),
                   'sp_mode': getattr(self.spec, 'sp_mode', 'ring')}

        def per_token(params, batch):
            with sharding_ctx(mesh, rules, manual_axes=manual,
                              options=options):
                if hasattr(model, 'per_token_loss_with_aux'):
                    nll, aux = model.per_token_loss_with_aux(params, batch)
                else:
                    nll = model.per_token_loss(params, batch)
                    aux = jnp.zeros((), jnp.float32)
                # aux (e.g. MoE balance) is computed per manual shard;
                # average to one well-defined replicated value
                for ax in manual:
                    aux = jax.lax.pmean(aux, ax)
                return nll, aux

        param_specs = jax.tree.map(
            self._manual_spec, self._axes_tree,
            is_leaf=lambda x: x is None or (
                isinstance(x, tuple) and
                all(isinstance(a, (str, type(None))) for a in x)))
        sp_on = AXIS_SEQUENCE in manual
        batch_spec = P(None, AXIS_SEQUENCE) if sp_on else P()
        from autodist_tpu.parallel.axes import shard_map_compat
        mapped = shard_map_compat(
            per_token, self.mesh,
            (param_specs, batch_spec),
            (P(None, AXIS_SEQUENCE) if sp_on else P(), P()),
            axis_names=set(manual))
        nll, aux = mapped(params, batch)
        mask = batch.get('mask') if hasattr(batch, 'get') else None
        if mask is not None:
            ce = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
        else:
            ce = jnp.mean(nll)
        return ce + getattr(self.model, 'aux_loss_weight', 0.0) * aux

    def _build_step(self, batch_struct):
        accum = max(1, int(getattr(self.spec, 'grad_accum', 1)))

        def grads_of(params, batch):
            """(loss, grads, state_updates) — updates is {} for
            stateless models."""
            from autodist_tpu.models.core import model_mode

            def loss_fn(p):
                with sharding_ctx(self.mesh, self.rules):
                    if not self._has_state:
                        return self.loss_for(p, batch), {}
                    with model_mode(training=True) as mm:
                        loss = self.loss_for(p, batch)
                    return loss, dict(mm.updates)
            if self.spec.remat == 'full':
                loss_fn = jax.checkpoint(loss_fn)
            (loss, updates), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            return loss, grads, updates

        def apply_updates(params, opt_updates, state_updates):
            from autodist_tpu.models.core import apply_tree_updates
            if not self._has_state:
                return jax.tree.map(
                    lambda p, u: p + u.astype(p.dtype),
                    params, opt_updates)
            # static bool mask: state leaves skip the optimizer entirely
            # (weight decay etc. must not touch running statistics) and
            # take their recorded updates instead
            new_params = jax.tree.map(
                lambda p, u, m: (p + u.astype(p.dtype)) if m else p,
                params, opt_updates, self._trainable_mask)
            return apply_tree_updates(new_params, state_updates)

        def step_fn(state, batch):
            if accum > 1:
                # split the leading (batch) dim into `accum` chunks and
                # scan, averaging loss and grads — exact parity with the
                # single-pass mean for equal chunks, at 1/accum the
                # activation memory
                def _chunk(x):
                    if x.shape[0] % accum:
                        raise ValueError(
                            'grad_accum=%d does not divide batch dim %d'
                            % (accum, x.shape[0]))
                    return x.reshape((accum, x.shape[0] // accum)
                                     + x.shape[1:])

                chunked = jax.tree.map(_chunk, batch)

                def body(acc, chunk):
                    loss_c, grads_c, upd_c = grads_of(state.params, chunk)
                    acc_loss, acc_grads, _ = acc
                    # state (BN EMA) keeps the LAST chunk's update: each
                    # chunk computes its EMA from the pre-step state, so
                    # the running stats advance once per optimizer step
                    # (semantics + tf.layers delta documented in
                    # docs/usage/parallelism.md "Gradient accumulation
                    # and BatchNorm statistics")
                    return (acc_loss + loss_c,
                            jax.tree.map(jnp.add, acc_grads, grads_c),
                            upd_c), None

                zero = (jnp.zeros((), jnp.float32),
                        jax.tree.map(
                            lambda p: jnp.zeros(p.shape, jnp.float32),
                            state.params),
                        self._initial_state_updates(state.params))
                (loss, grads, state_updates), _ = jax.lax.scan(
                    body, zero, chunked)
                loss = loss / accum
                grads = jax.tree.map(lambda g: g / accum, grads)
            else:
                loss, grads, state_updates = grads_of(state.params, batch)
            updates, new_opt = self.optimizer.update(
                grads, state.opt_state, state.params)
            new_params = apply_updates(state.params, updates,
                                       state_updates)
            return TrainState(params=new_params, opt_state=new_opt,
                              step=state.step + 1), {'loss': loss}

        return step_fn

    def _initial_state_updates(self, params):
        """Scan carry skeleton for state updates: current values of the
        non-trainable leaves (so chunk 1's replacement has a matching
        structure)."""
        if not self._has_state:
            return {}
        out = {}
        for path in self._state_paths:
            node = params
            for key in path:
                node = node[key]
            out[path] = node
        return out

    def _step_key(self, batch):
        struct = jax.tree.structure(batch)
        shapes = tuple((tuple(np.shape(x)), np.asarray(x).dtype.str
                        if not hasattr(x, 'dtype') else str(x.dtype))
                       for x in jax.tree.leaves(batch))
        return (struct, shapes)

    def _ensure_step(self, key, state, batch):
        if key not in self._step_cache:
            step_fn = self._build_step(jax.tree.structure(batch))
            param_sh = self._param_sharding_tree(state.params)
            opt_sh = self._opt_sharding(state.opt_state, state.params,
                                        param_sh)
            state_sh = TrainState(params=param_sh, opt_state=opt_sh,
                                  step=NamedSharding(self.mesh, P()))
            self._step_cache[key] = jax.jit(
                step_fn,
                in_shardings=(state_sh, self.batch_sharding(batch)),
                out_shardings=(state_sh, None),
                donate_argnums=(0,) if self._donate else ())
        return self._step_cache[key]

    def compile_step(self, state, batch):
        """AOT-compile the step for this batch signature, ONCE, and make
        subsequent ``step`` calls with the same signature reuse the same
        executable. Returns the ``jax.stages.Compiled`` (which exposes
        ``cost_analysis()`` — used by bench.py for FLOP cross-checks)."""
        key = self._step_key(batch)
        fn = self._ensure_step(key, state, batch)
        if isinstance(fn, jax.stages.Compiled):
            return fn
        compiled = fn.lower(state, self.shard_batch(batch)).compile()
        self._step_cache[key] = compiled
        return compiled

    def step(self, state, batch):
        """One optimizer step; returns (new_state, metrics)."""
        key = self._step_key(batch)
        fn = self._ensure_step(key, state, batch)
        batch = self.shard_batch(batch)
        return fn(state, batch)

    # -- fit/evaluate conveniences (reference case c7's Model.fit role) ----
    def fit(self, state, data, steps=None, eval_data=None, eval_every=0,
            checkpoint_manager=None, save_every=0, prefetch=0):
        """Train over an iterable of batches (c7 ``Model.fit`` role).

        Args:
            state: TrainState from :meth:`init`.
            data: iterable (or iterator) of batch dicts.
            prefetch: keep this many device-placed batches in flight so
                host->device transfer overlaps compute (0 = off). Safe
                with :meth:`step`: already-placed arrays pass through
                its ``shard_batch`` untouched. NB with ``steps=N`` the
                prefetcher reads up to ``prefetch`` batches PAST the
                N-th from ``data`` — don't share one live iterator
                across fit() phases with prefetch on.
            steps: stop after this many steps (None = exhaust ``data``).
            eval_data: optional sequence of eval batches.
            eval_every: run :meth:`evaluate` every N steps (0 = only at
                the end when ``eval_data`` is given).
            checkpoint_manager: optional CheckpointManager; the FULL
                state (params + optimizer slots + step) is saved every
                ``save_every`` steps and at the end, enabling exact
                resume via :meth:`restore_state`.
            save_every: checkpoint cadence (0 = only at the end).

        Returns:
            (state, history) where history is a dict with 'loss' (one
            entry per step) and, when evaluating, 'eval_loss' entries of
            (step, loss).
        """
        history = {'loss': []}
        if eval_data is not None:
            history['eval_loss'] = []
        if prefetch:
            from autodist_tpu.data.prefetch import prefetch_to_device
            data = prefetch_to_device(data, self.shard_batch,
                                      size=prefetch)
        it = iter(data)
        n = 0
        for batch in it:
            state, metrics = self.step(state, batch)
            history['loss'].append(float(metrics['loss']))
            n += 1
            if eval_data is not None and eval_every and \
                    n % eval_every == 0:
                history['eval_loss'].append(
                    (n, self.evaluate(state, eval_data)))
            if checkpoint_manager is not None and save_every and \
                    n % save_every == 0:
                self.save_state(checkpoint_manager, state)
            if steps is not None and n >= steps:
                break
        if eval_data is not None and (not eval_every or
                                      n % eval_every):
            history['eval_loss'].append((n, self.evaluate(state,
                                                          eval_data)))
        if checkpoint_manager is not None and (not save_every or
                                               n % save_every):
            self.save_state(checkpoint_manager, state)
        if checkpoint_manager is not None and \
                hasattr(checkpoint_manager, 'wait_until_finished'):
            checkpoint_manager.wait_until_finished()   # drain async save
        return state, history

    def evaluate(self, state, batches, metrics_fn=None):
        """Mean loss over batches without updating state (c7
        ``Model.evaluate`` role).

        With ``metrics_fn(params, batch) -> {name: scalar}`` (e.g. an
        accuracy), returns ``{'loss': ..., **means of metrics}``
        instead of the bare loss. Pass a STABLE function object — the
        compiled evaluator is cached per (batch signature, metrics_fn),
        so a fresh lambda per call recompiles (the cache is bounded, so
        this leaks time, not memory).
        """
        if not hasattr(self, '_eval_cache'):
            self._eval_cache = {}
        if len(self._eval_cache) > 16:   # bound churn from unstable fns
            self._eval_cache.clear()
        totals, count = {}, 0
        for batch in batches:
            # key by the metrics_fn itself: different fns with the same
            # batch signature must not share a compiled evaluator
            key = (self._step_key(batch), metrics_fn)

            if key not in self._eval_cache:
                def eval_fn(params, batch):
                    # same sharding context as step: constrain() hints
                    # and sharding-aware module paths (e.g. the sharded
                    # embedding lookup) stay active during eval; eval
                    # mode makes BatchNorm use its running statistics
                    from autodist_tpu.models.core import model_mode
                    with sharding_ctx(self.mesh, self.rules), \
                            model_mode(training=False):
                        out = {'loss': self.loss_for(params, batch)}
                        if metrics_fn is not None:
                            out.update(metrics_fn(params, batch))
                    return out
                self._eval_cache[key] = jax.jit(eval_fn)
            batch = self.shard_batch(batch)
            for name, val in self._eval_cache[key](state.params,
                                                   batch).items():
                totals[name] = totals.get(name, 0.0) + float(val)
            count += 1
        means = {name: val / max(count, 1)
                 for name, val in totals.items()}
        return means if metrics_fn is not None else means.get('loss', 0.0)

    # -- checkpoint/resume of the FULL training state ----------------------
    def state_sharding(self, state):
        """TrainState of NamedShardings matching how ``step`` places
        this state on the mesh."""
        param_sh = self._param_sharding_tree(state.params)
        opt_sh = self._opt_sharding(state.opt_state, state.params,
                                    param_sh)
        return TrainState(params=param_sh, opt_state=opt_sh,
                          step=NamedSharding(self.mesh, P()))

    def save_state(self, manager, state):
        """Checkpoint params + optimizer state + step for exact resume
        (the reference's saver covers variables only; optimizer slots
        ride along here so training continues bit-for-bit).

        Multi-host: the orbax backend receives the live (sharded) arrays
        and writes per-host shards itself; the npy backend gathers
        non-addressable leaves across processes first.
        """
        step = int(jax.device_get(state.step))
        if getattr(manager, 'backend', 'npy') == 'orbax':
            return manager.save(step, state)

        def to_host(x):
            if hasattr(x, 'is_fully_addressable') and \
                    not x.is_fully_addressable:
                from jax.experimental import multihost_utils
                x = multihost_utils.process_allgather(x, tiled=True)
            return np.asarray(jax.device_get(x))
        host = jax.tree.map(to_host, state)   # collective: all processes
        if jax.process_count() > 1 and jax.process_index() != 0:
            return None   # one writer for the self-contained npy layout
        return manager.save(step, host)

    def restore_state(self, manager, state_template, step=None):
        """Restore a :meth:`save_state` checkpoint onto this trainer's
        mesh (any mesh — the files are logical layout). Returns
        ``state_template`` unchanged when no checkpoint exists."""
        # shape/dtype skeleton, not device_get: the template may span
        # non-addressable devices in multi-host runs
        like = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                           getattr(x, 'dtype',
                                                   jnp.float32)),
            state_template)
        tree, got_step = manager.restore(like=like, step=step)
        if tree is None:
            return state_template, None
        shardings = self.state_sharding(state_template)
        state = jax.tree.map(
            lambda x, sh: jax.device_put(jnp.asarray(x), sh),
            tree, shardings)
        return state, got_step

    # -- profiling (session path has RunOptions; this is the Trainer's) ----
    def profile(self, state, batch, trace_dir, steps=3):
        """Capture a ``jax.profiler`` trace (TensorBoard/Perfetto) of
        ``steps`` compiled training steps — the functional-path analogue
        of the session's ``RunOptions(trace_level=...)`` (reference
        chrome-trace timelines, runner.py:64-75). Returns ``trace_dir``;
        the traced steps' state updates are DISCARDED (profiling must
        not perturb training)."""
        import os
        fn = self.compile_step(state, batch)
        placed = self.shard_batch(batch)
        # profile a COPY when the step donates its input state (the
        # default): donating the caller's state would invalidate their
        # buffers. Without donation the copy would only waste HBM.
        s = jax.tree.map(jnp.copy, state) if self._donate else state
        s, m = fn(s, placed)           # warmup outside the trace
        jax.block_until_ready(m['loss'])
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
        try:
            for _ in range(steps):
                s, m = fn(s, placed)
            jax.block_until_ready(m['loss'])
        finally:
            jax.profiler.stop_trace()
        logging.info('Profiler trace (%d steps) written to %s',
                     steps, trace_dir)
        return trace_dir

    # -- fetch helpers (reference get-variable parity) ---------------------
    def get_params(self, state):
        """Gather params to host in logical (unsharded) layout."""
        return jax.tree.map(np.asarray, jax.device_get(state.params))
