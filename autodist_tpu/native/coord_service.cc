// Coordination service: TCP key/value + counters + barriers + a binary
// tensor data plane.
//
// TPU-native replacement for the control-plane primitives the reference
// gets from the TF C++ runtime (SURVEY.md §2.2): FIFO token queues for
// sync barriers and bounded staleness (ps_synchronizer.py:335-458) and
// the chief/worker rendezvous that tf.Server+grpc provided. SPMD
// collectives need none of this inside a program; this service covers the
// *between-program* coordination: multi-process barriers, bounded-
// staleness windows (each worker publishes its step; a worker may run
// ahead only while min_step >= my_step - staleness), heartbeats for
// fail-fast monitoring, and small metadata exchange (strategy ids).
//
// The binary tensor commands (BSET/BGET/BADD/BSTEP) are the PS data
// plane: the reference aggregates cross-worker gradients in
// ConditionalAccumulators living on the PS task and rides TF's grpc
// data plane for the bytes (ps_synchronizer.py:556-633); here workers
// push deltas/gradients as length-prefixed raw frames (f32 or bf16 on
// the wire, f32 at rest) applied with an atomic elementwise add —
// commutative apply-per-push, which is exactly the reference's
// staleness>0 accumulator mode (take_grad(1): every push is applied).
// Each tensor has its OWN mutex, so a multi-MB push on one variable
// never serializes against another variable's pull; a run hosts one
// service per PS endpoint (ps_lb_strategy.py:64-83 bin-packing made
// load-bearing: variables land on the endpoint their
// reduction_destination resolves to).
//
// BSTEP additionally keeps the optimizer step ON the PS (the reference
// re-creates the optimizer over PS-resident variables so async workers
// share slot state, kernel/partitioner.py:570-573): workers push raw
// gradients and the service applies SGD/momentum with a PS-resident
// velocity slot shared by all workers.
//
// Protocol: newline-terminated text commands over TCP; the B* commands
// carry a length-prefixed raw payload immediately after the newline.
//   SET <key> <value>            -> OK
//   GET <key>                    -> VAL <value> | NONE
//   DEL <key>                    -> OK
//   INCR <key> <delta>           -> VAL <n>        (atomic add, int64)
//   WAITGE <key> <n> <ms>        -> VAL <m> | TIMEOUT   (wait key >= n)
//   MINWAIT <prefix> <n> <k> <ms>-> VAL <min> | TIMEOUT
//       (wait until >=k keys share <prefix> and their min value >= n)
//   BARRIER <name> <k> <ms>      -> OK | TIMEOUT   (k-party barrier)
//   BSET <key> <nbytes> <wire>   [payload] -> OK
//       (store tensor; wire dtype f32|bf16, stored as f32)
//   BGET <key> <wire>            -> VAL <nbytes>\n[payload] | NONE
//   BADD <key> <nbytes> <wire>   [payload] -> VAL <n>
//       (atomic elementwise += ; creates the tensor if absent; returns
//        the tensor's accumulated push count)
//   BSTEP <key> <nbytes> <wire> <lr> <momentum> [payload] -> VAL <n>
//       (payload is a GRADIENT; service applies vel = m*vel + g,
//        tensor -= lr*vel with the velocity slot resident here)
//   PING                         -> PONG
//   SHUTDOWN                     -> OK (server exits)
//
// Build: g++ -O2 -std=c++17 -pthread -o coord_service coord_service.cc

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

// A stored tensor. `mu` serializes element updates per KEY (not
// globally): the scoped-allocator-scale concern of one global lock over
// all variables does not exist here.
struct Tensor {
  std::mutex mu;
  std::vector<float> data;
  std::vector<float> vel;  // PS-resident momentum slot (BSTEP)
  int64_t pushes = 0;
};

struct Store {
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;
  std::map<std::string, int64_t> counters;
  std::map<std::string, int64_t> barrier_arrivals;
  std::map<std::string, int64_t> barrier_generation;
  std::map<std::string, std::shared_ptr<Tensor>> tensors;
  std::atomic<bool> shutting_down{false};
};

Store g_store;

std::shared_ptr<Tensor> find_tensor(const std::string& key, bool create) {
  std::lock_guard<std::mutex> l(g_store.mu);
  auto it = g_store.tensors.find(key);
  if (it != g_store.tensors.end()) return it->second;
  if (!create) return nullptr;
  auto t = std::make_shared<Tensor>();
  g_store.tensors[key] = t;
  return t;
}

// -- wire dtypes -------------------------------------------------------------

uint16_t f32_to_bf16(float f) {
  uint32_t u;
  memcpy(&u, &f, 4);
  // NaN first: rtne rounding would carry a small-mantissa NaN into Inf
  if ((u & 0x7fffffffu) > 0x7f800000u)
    return static_cast<uint16_t>((u >> 16) | 0x0040);  // quiet NaN
  // round-to-nearest-even, like XLA's f32->bf16 convert
  uint32_t bias = 0x7fff + ((u >> 16) & 1);
  return static_cast<uint16_t>((u + bias) >> 16);
}

float bf16_to_f32(uint16_t h) {
  uint32_t u = static_cast<uint32_t>(h) << 16;
  float f;
  memcpy(&f, &u, 4);
  return f;
}

// wire "f32": payload is raw little-endian float32; "bf16": raw uint16
// upper halves of float32. Returns false on a malformed payload.
bool decode_wire(const std::string& payload, const std::string& wire,
                 std::vector<float>* out) {
  if (wire == "f32") {
    if (payload.size() % 4) return false;
    out->resize(payload.size() / 4);
    memcpy(out->data(), payload.data(), payload.size());
    return true;
  }
  if (wire == "bf16") {
    if (payload.size() % 2) return false;
    size_t n = payload.size() / 2;
    out->resize(n);
    const uint16_t* src =
        reinterpret_cast<const uint16_t*>(payload.data());
    for (size_t i = 0; i < n; ++i) (*out)[i] = bf16_to_f32(src[i]);
    return true;
  }
  return false;
}

bool encode_wire(const std::vector<float>& v, const std::string& wire,
                 std::string* out) {
  if (wire == "f32") {
    out->assign(reinterpret_cast<const char*>(v.data()), v.size() * 4);
    return true;
  }
  if (wire == "bf16") {
    out->resize(v.size() * 2);
    uint16_t* dst = reinterpret_cast<uint16_t*>(&(*out)[0]);
    for (size_t i = 0; i < v.size(); ++i) dst[i] = f32_to_bf16(v[i]);
    return true;
  }
  return false;
}

int64_t counter_of(const std::string& key) {
  auto it = g_store.counters.find(key);
  return it == g_store.counters.end() ? 0 : it->second;
}

// min over counters with the prefix; count reported via out param.
int64_t prefix_min(const std::string& prefix, int* count) {
  int64_t min_v = INT64_MAX;
  int n = 0;
  for (auto it = g_store.counters.lower_bound(prefix);
       it != g_store.counters.end() &&
       it->first.compare(0, prefix.size(), prefix) == 0;
       ++it) {
    ++n;
    if (it->second < min_v) min_v = it->second;
  }
  *count = n;
  return n ? min_v : 0;
}

// Payload bytes that follow the header line, or 0 for text commands.
size_t payload_size(const std::string& line) {
  std::istringstream in(line);
  std::string cmd, key;
  in >> cmd;
  if (cmd != "BSET" && cmd != "BADD" && cmd != "BSTEP") return 0;
  size_t nbytes = 0;
  in >> key >> nbytes;
  return nbytes;
}

// Handles one request. `payload` holds the request's raw bytes (B*
// commands); a BGET reply's bytes land in `reply_payload` and follow the
// returned header line on the wire.
std::string handle(const std::string& line, const std::string& payload,
                   std::string* reply_payload) {
  std::istringstream in(line);
  std::string cmd;
  in >> cmd;
  using namespace std::chrono;
  if (cmd == "PING") return "PONG";
  if (cmd == "SET") {
    std::string k, v;
    in >> k;
    std::getline(in, v);
    if (!v.empty() && v[0] == ' ') v.erase(0, 1);
    std::lock_guard<std::mutex> l(g_store.mu);
    g_store.kv[k] = v;
    g_store.cv.notify_all();
    return "OK";
  }
  if (cmd == "GET") {
    std::string k;
    in >> k;
    std::lock_guard<std::mutex> l(g_store.mu);
    auto it = g_store.kv.find(k);
    return it == g_store.kv.end() ? "NONE" : ("VAL " + it->second);
  }
  if (cmd == "DEL") {
    std::string k;
    in >> k;
    std::lock_guard<std::mutex> l(g_store.mu);
    g_store.kv.erase(k);
    g_store.counters.erase(k);
    return "OK";
  }
  if (cmd == "INCR") {
    std::string k;
    int64_t d = 1;
    in >> k >> d;
    std::lock_guard<std::mutex> l(g_store.mu);
    int64_t v = (g_store.counters[k] += d);
    g_store.cv.notify_all();
    return "VAL " + std::to_string(v);
  }
  if (cmd == "WAITGE") {
    std::string k;
    int64_t n = 0, ms = 0;
    in >> k >> n >> ms;
    std::unique_lock<std::mutex> l(g_store.mu);
    bool ok = g_store.cv.wait_for(l, milliseconds(ms), [&] {
      return counter_of(k) >= n || g_store.shutting_down;
    });
    if (!ok || g_store.shutting_down) return "TIMEOUT";
    return "VAL " + std::to_string(counter_of(k));
  }
  if (cmd == "MINWAIT") {
    std::string prefix;
    int64_t n = 0, k = 0, ms = 0;
    in >> prefix >> n >> k >> ms;
    std::unique_lock<std::mutex> l(g_store.mu);
    int count = 0;
    bool ok = g_store.cv.wait_for(l, milliseconds(ms), [&] {
      int c = 0;
      int64_t m = prefix_min(prefix, &c);
      return (c >= k && m >= n) || g_store.shutting_down;
    });
    if (!ok || g_store.shutting_down) return "TIMEOUT";
    return "VAL " + std::to_string(prefix_min(prefix, &count));
  }
  if (cmd == "BARRIER") {
    std::string name;
    int64_t k = 0, ms = 0;
    in >> name >> k >> ms;
    std::unique_lock<std::mutex> l(g_store.mu);
    int64_t gen = g_store.barrier_generation[name];
    int64_t arrived = ++g_store.barrier_arrivals[name];
    if (arrived >= k) {
      g_store.barrier_arrivals[name] = 0;
      ++g_store.barrier_generation[name];
      g_store.cv.notify_all();
      return "OK";
    }
    bool ok = g_store.cv.wait_for(l, milliseconds(ms), [&] {
      return g_store.barrier_generation[name] != gen ||
             g_store.shutting_down;
    });
    if (ok && !g_store.shutting_down) return "OK";
    // Withdraw this party's arrival so a timeout doesn't poison the
    // barrier name: a later round must still need k live arrivals. Only
    // if the round we joined never completed (generation unchanged).
    if (g_store.barrier_generation[name] == gen &&
        g_store.barrier_arrivals[name] > 0) {
      --g_store.barrier_arrivals[name];
    }
    return "TIMEOUT";
  }
  if (cmd == "BSET") {
    std::string k, wire;
    size_t nbytes = 0;
    in >> k >> nbytes >> wire;
    std::vector<float> vals;
    if (!decode_wire(payload, wire, &vals)) return "ERR bad payload";
    std::shared_ptr<Tensor> t = find_tensor(k, /*create=*/true);
    std::lock_guard<std::mutex> l(t->mu);
    t->data = std::move(vals);
    t->vel.clear();
    t->pushes = 0;
    return "OK";
  }
  if (cmd == "BGET") {
    std::string k, wire;
    in >> k >> wire;
    if (wire.empty()) wire = "f32";
    std::shared_ptr<Tensor> t = find_tensor(k, /*create=*/false);
    if (!t) return "NONE";
    {
      std::lock_guard<std::mutex> l(t->mu);
      if (!encode_wire(t->data, wire, reply_payload))
        return "ERR bad wire dtype";
    }
    return "VAL " + std::to_string(reply_payload->size());
  }
  if (cmd == "BADD") {
    std::string k, wire;
    size_t nbytes = 0;
    in >> k >> nbytes >> wire;
    std::vector<float> delta;
    if (!decode_wire(payload, wire, &delta)) return "ERR bad payload";
    std::shared_ptr<Tensor> t = find_tensor(k, /*create=*/true);
    std::lock_guard<std::mutex> l(t->mu);
    if (t->data.empty()) t->data.assign(delta.size(), 0.f);
    if (t->data.size() != delta.size()) return "ERR shape mismatch";
    for (size_t i = 0; i < delta.size(); ++i) t->data[i] += delta[i];
    return "VAL " + std::to_string(++t->pushes);
  }
  if (cmd == "BSTEP") {
    std::string k, wire;
    size_t nbytes = 0;
    double lr = 0.0, momentum = 0.0;
    in >> k >> nbytes >> wire >> lr >> momentum;
    std::vector<float> grad;
    if (!decode_wire(payload, wire, &grad)) return "ERR bad payload";
    std::shared_ptr<Tensor> t = find_tensor(k, /*create=*/false);
    if (!t) return "ERR no tensor";
    std::lock_guard<std::mutex> l(t->mu);
    if (t->data.size() != grad.size()) return "ERR shape mismatch";
    if (momentum != 0.0 && t->vel.empty())
      t->vel.assign(grad.size(), 0.f);
    if (momentum != 0.0) {
      const float m = static_cast<float>(momentum);
      const float a = static_cast<float>(lr);
      for (size_t i = 0; i < grad.size(); ++i) {
        t->vel[i] = m * t->vel[i] + grad[i];
        t->data[i] -= a * t->vel[i];
      }
    } else {
      const float a = static_cast<float>(lr);
      for (size_t i = 0; i < grad.size(); ++i)
        t->data[i] -= a * grad[i];
    }
    return "VAL " + std::to_string(++t->pushes);
  }
  if (cmd == "SHUTDOWN") {
    std::lock_guard<std::mutex> l(g_store.mu);
    g_store.shutting_down = true;
    g_store.cv.notify_all();
    return "OK";
  }
  return "ERR unknown command";
}

bool send_all(int fd, const char* data, size_t len) {
  while (len) {
    ssize_t n = send(fd, data, len, 0);
    if (n <= 0) return false;
    data += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

void serve_conn(int fd) {
  std::string buf;
  char chunk[1 << 16];
  while (!g_store.shutting_down) {
    // one header line
    size_t pos;
    while ((pos = buf.find('\n')) == std::string::npos) {
      ssize_t n = recv(fd, chunk, sizeof(chunk), 0);
      if (n <= 0) {
        close(fd);
        return;
      }
      buf.append(chunk, n);
    }
    std::string line = buf.substr(0, pos);
    buf.erase(0, pos + 1);
    if (!line.empty() && line.back() == '\r') line.pop_back();
    // then that command's declared payload bytes
    size_t need = payload_size(line);
    while (buf.size() < need) {
      ssize_t n = recv(fd, chunk, sizeof(chunk), 0);
      if (n <= 0) {
        close(fd);
        return;
      }
      buf.append(chunk, n);
    }
    std::string payload = buf.substr(0, need);
    buf.erase(0, need);
    std::string reply_payload;
    std::string resp = handle(line, payload, &reply_payload) + "\n";
    if (!send_all(fd, resp.data(), resp.size()) ||
        (!reply_payload.empty() &&
         !send_all(fd, reply_payload.data(), reply_payload.size()))) {
      close(fd);
      return;
    }
    if (g_store.shutting_down) {  // reply sent; exit promptly —
      close(fd);                  // accept() would otherwise block
      _exit(0);
    }
  }
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  int port = argc > 1 ? atoi(argv[1]) : 14998;
  // Bind address: second arg; loopback unless the launcher asks for more
  // (multi-host runs pass 0.0.0.0 or the coordinator interface).
  const char* bind_addr = argc > 2 ? argv[2] : "127.0.0.1";
  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = inet_addr(bind_addr);
  addr.sin_port = htons(port);
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(srv, 128) != 0) {
    perror("listen");
    return 1;
  }
  fprintf(stderr, "coord_service listening on :%d\n", port);
  fflush(stderr);
  std::vector<std::thread> threads;
  while (!g_store.shutting_down) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) break;
    threads.emplace_back(serve_conn, fd);
  }
  close(srv);
  for (auto& t : threads)
    if (t.joinable()) t.detach();
  return 0;
}
