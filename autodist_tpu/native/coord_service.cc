// Coordination service: TCP key/value + counters + barriers + a binary
// tensor data plane.
//
// TPU-native replacement for the control-plane primitives the reference
// gets from the TF C++ runtime (SURVEY.md §2.2): FIFO token queues for
// sync barriers and bounded staleness (ps_synchronizer.py:335-458) and
// the chief/worker rendezvous that tf.Server+grpc provided. SPMD
// collectives need none of this inside a program; this service covers the
// *between-program* coordination: multi-process barriers, bounded-
// staleness windows (each worker publishes its step; a worker may run
// ahead only while min_step >= my_step - staleness), heartbeats for
// fail-fast monitoring, and small metadata exchange (strategy ids).
//
// The binary tensor commands (BSET/BGET/BADD/BSTEP, and the row-sparse
// BSADD/BGETROWS) are the PS data
// plane: the reference aggregates cross-worker gradients in
// ConditionalAccumulators living on the PS task and rides TF's grpc
// data plane for the bytes (ps_synchronizer.py:556-633); here workers
// push deltas/gradients as length-prefixed raw frames (f32, bf16 or
// block-quantized i8 on the wire, f32 at rest) applied with an atomic
// elementwise add —
// commutative apply-per-push, which is exactly the reference's
// staleness>0 accumulator mode (take_grad(1): every push is applied).
// Each tensor has its OWN mutex, so a multi-MB push on one variable
// never serializes against another variable's pull; a run hosts one
// service per PS endpoint (ps_lb_strategy.py:64-83 bin-packing made
// load-bearing: variables land on the endpoint their
// reduction_destination resolves to, per SHARD for partitioned
// variables — partitioned_ps_strategy.py:89-96 round-robin placement).
//
// All B* commands accept an optional trailing `<off_elems> <total_elems>`
// range so large tensors move as bounded chunks (the client splits
// frames above AUTODIST_PS_CHUNK_BYTES): every update rule here is
// elementwise, so ranged application is exact. A logical push counts
// once, at its offset-0 chunk.
//
// Row-sparse tensor protocol (embedding variables): a push whose delta
// touches few rows of a [rows, cols] table ships ONLY those rows.
// BSADD's payload is `<nrows> little-endian int32 row indices ||
// <nrows> rows of wire data` (row_bytes wire bytes per row; cols =
// row_bytes / wire itemsize), applied as a scatter-add into the stored
// tensor under its lock — addition commutes, so concurrent sparse and
// dense pushes interleave exactly, and a delta whose untouched rows
// are exactly zero loses nothing by dropping them. The optional
// `<off> <total>` range counts ROWS of the logical push (the client
// splits large row sets into chunks); fencing, the torn-read version
// counter and chunk-sequence aborts behave exactly like BADD. BGETROWS
// returns just the listed rows (its request payload is the int32
// index vector), for refreshing a worker's proxy cache after a sparse
// push without refetching the whole table.
//
// BSTEP keeps the optimizer step ON the PS (the reference re-creates
// the user's optimizer over PS-resident variables so async workers
// share slot state, kernel/partitioner.py:570-573): workers push raw
// gradients and the service applies the named update rule with PS-
// resident slots shared by all workers. Rules (optax-matching forms):
//   sgd      p0=lr p1=momentum   vel = m*vel + g; w -= lr*vel
//   adam     p0=lr p1=b1 p2=b2 p3=eps
//            m=b1*m+(1-b1)g; v=b2*v+(1-b2)g^2;
//            w -= lr * (m/(1-b1^t)) / (sqrt(v/(1-b2^t)) + eps)
//   adagrad  p0=lr p1=eps p2=init_acc
//            acc += g^2; w -= lr * g / (sqrt(acc) + eps)
// The adam step index t is shared: a push's offset-0 chunk with t=0
// bumps the tensor's counter and the reply returns the t used; later
// chunks of the same push pass that t explicitly.
//
// Authentication: when the service is started with AUTODIST_COORD_TOKEN
// set, every connection is greeted with `HELLO <nonce>` and must present
// `AUTH <hex hmac-sha256(token, nonce)>` before any other command
// (without a token the greeting is `HELLO open`). The reference's
// control plane rode authenticated SSH (coordinator.py:46-90); an open
// TCP port on a multi-host NIC needs at least this shared-secret
// handshake.
//
// Protocol: newline-terminated text commands over TCP; the B* commands
// carry a length-prefixed raw payload immediately after the newline.
// Writer fencing (elastic recovery): `FENCE <key> <gen>` binds the
// connection to generation <gen> of the counter <key>. Once that
// counter advances past the bound generation (a survivor or the
// supervising coordinator declared this writer dead and bumped it),
// every mutating command on the connection — SET, DEL, DELNS, INCR,
// BSET, BADD, BSADD, BSTEP — is rejected with `ERR fenced`, so a zombie can
// never corrupt state after its replacement joins under a fresh
// generation. Reads and waits stay open (a zombie observing the world
// is harmless; only its writes are dangerous).
//
//   AUTH <hmac-hex>              -> OK | ERR (connection greeting reply)
//   FENCE <key> <gen>            -> OK | ERR fenced (bind this
//                                    connection's writer generation)
//   SET <key> <value>            -> OK
//   GET <key>                    -> VAL <value> | NONE
//   DEL <key>                    -> OK
//   DELNS <prefix>               -> VAL <n>  (purge keys/counters/tensors
//                                    /barriers under prefix: run-end
//                                    cleanup for long-lived endpoints)
//   INCR <key> <delta>           -> VAL <n>        (atomic add, int64)
//   WAITGE <key> <n> <ms>        -> VAL <m> | TIMEOUT   (wait key >= n)
//   MINWAIT <prefix> <n> <k> <ms>-> VAL <min> | TIMEOUT
//       (wait until >=k keys share <prefix> and their min value >= n)
//   BARRIER <name> <k> <ms>      -> OK | TIMEOUT   (k-party barrier)
//   BSET <key> <nbytes> <wire> [<off> <total>]  [payload] -> OK
//       (store tensor; wire dtype f32|bf16|i8, stored as f32. The i8
//        wire is the blockscale format: `u32 block, u32 n, f32 scales
//        x ceil(n/block), int8 q x n` — one f32 scale per `block`
//        int8 values, value[i] = q[i] * scale[i/block]. The block
//        size rides in the frame itself, so any client block size
//        (AUTODIST_QUANT_BLOCK) decodes)
//   BGET <key> <wire> [<off> <count>] [v] -> VAL <nbytes> [<ver>]\n
//       [payload] | NONE   ("v" opts into <ver> = version*2 +
//        write_in_progress; odd or chunk-to-chunk-changing ver = torn
//        read, client retries)
//   BADD <key> <nbytes> <wire> [<off> <total>]  [payload] -> VAL <n>
//       (atomic elementwise += ; creates the tensor if absent; returns
//        the tensor's accumulated push count)
//   BSADD <key> <nrows> <row_bytes> <wire> [<off> <total>]  [payload]
//       -> VAL <n>   (row-sparse scatter-add: payload is <nrows> int32
//        row indices then <nrows> rows of wire data; <off>/<total>
//        count ROWS of the logical push; tensor must already exist.
//        For the i8 wire, <row_bytes> is the TOTAL byte length of the
//        encoded rows blob — blockscale frames carry a scales header,
//        so their size is not per-row divisible — and cols is derived
//        from decoded elements / nrows; f32/bf16 keep the per-row
//        meaning)
//   BGETROWS <key> <nrows> <ncols> <wire> [v]  [payload] -> VAL
//       <nbytes> [<ver>]\n[payload]  | NONE   (fetch just the rows
//        listed in the int32 request payload; "v" = version field,
//        same torn-read semantics as BGET)
//   BSTAT <key>                  -> VAL <pushes> <steps> <elems>
//                                   <slot1> <slot2> | NONE
//   BSTEP <key> <nbytes> <wire> <rule> <t> <p0> <p1> <p2> <p3>
//         [<off> <total>]        [payload] -> VAL <t_used>
//   PING                         -> PONG
//   SHUTDOWN                     -> OK (server exits)
//
// Build: g++ -O3 -std=c++17 -pthread -o coord_service coord_service.cc

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <set>
#include <sstream>
#include <string>
#include <string_view>
#include <thread>
#include <vector>

namespace {

// Declared payload sizes above this are refused outright (ADVICE r3:
// an unvalidated size_t let a malformed header buffer unbounded bytes).
constexpr uint64_t kMaxPayload = 1ULL << 32;  // 4 GB per frame
constexpr size_t kBadPayload = static_cast<size_t>(-1);

// A stored tensor. `mu` serializes element updates per KEY (not
// globally): the scoped-allocator-scale concern of one global lock over
// all variables does not exist here.
struct Tensor {
  std::mutex mu;
  std::vector<float> data;
  std::vector<float> slot1;  // PS-resident momentum / adam first moment
  std::vector<float> slot2;  // adam second moment / adagrad accumulator
  int64_t pushes = 0;
  int64_t steps = 0;  // BSTEP optimizer-step counter (adam bias t)
  // Torn-read detection (ADVICE r4).  `version` bumps on every
  // mutating frame (each BSET chunk, BADD, BSTEP); `open_writes`
  // counts chunked write sequences in flight (first chunk ++, final
  // chunk --, so a single whole-tensor frame nets 0 inside its own
  // lock hold).  A BGET that opts in (trailing "v") gets
  // `version*2 + (open_writes>0)` in its reply: an odd value or a
  // value that moves across a reader's chunks means the read raced a
  // writer and must be retried.  Every error reply closes the sequence
  // it opened (abort), so a rejected write cannot wedge the counter,
  // and a writer whose CONNECTION dies mid-sequence has its open
  // sequences aborted at disconnect (serve_conn's SeqAborter) — only a
  // writer alive-but-stalled past the client's stall window surfaces
  // to readers as a stalled-odd error rather than torn data.
  int64_t version = 0;
  int64_t open_writes = 0;
};

struct Store {
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;
  std::map<std::string, int64_t> counters;
  std::map<std::string, int64_t> barrier_arrivals;
  std::map<std::string, int64_t> barrier_generation;
  std::map<std::string, std::shared_ptr<Tensor>> tensors;
  std::atomic<bool> shutting_down{false};
};

Store g_store;
std::string g_token;  // empty = open service (loopback-only deployments)

// Per-connection writer fencing. A connection that bound itself to a
// fence counter via FENCE is a generation-g writer; once the counter
// advances past g every mutating command on the connection is
// rejected. Unfenced connections (fence_key empty) write freely — the
// pre-recovery protocol, and reads never fence.
struct ConnState {
  std::string fence_key;
  int64_t fence_gen = 0;
  // Chunked write sequences THIS connection opened (offset-0 frame
  // seen, final chunk not yet) — touched only by the connection's own
  // serving thread. Aborted when the connection dies: a writer killed
  // between chunks (the exclude/restart policies' died-mid-push case)
  // sends no further frames, so without this the sequence would hold
  // open_writes forever and wedge every reader on odd parity until a
  // DELNS. TCP teardown (os._exit, host crash with RST, clean close)
  // lands here as read_line/recv failure.
  std::set<std::string> open_seqs;
};

constexpr const char* kFencedErr = "ERR fenced stale generation";

// True when the connection's bound generation has been superseded.
// Caller must hold g_store.mu. The KV/counter mutations check under
// the SAME mu hold as the mutation itself — a separate check-then-act
// would let one in-flight zombie frame commit after its fence bump.
bool is_fenced_locked(const ConnState& conn) {
  if (conn.fence_key.empty()) return false;
  auto it = g_store.counters.find(conn.fence_key);
  int64_t cur = it == g_store.counters.end() ? 0 : it->second;
  return cur > conn.fence_gen;
}

// Locking variant. Takes g_store.mu; safe to call while holding a
// tensor mutex (nothing acquires a tensor mutex under g_store.mu), so
// the B* handlers re-check AFTER taking the tensor lock: once a fence
// bump's INCR has been processed, no later-processed frame from the
// stale writer can mutate the tensor.
bool is_fenced(const ConnState& conn) {
  if (conn.fence_key.empty()) return false;
  std::lock_guard<std::mutex> l(g_store.mu);
  return is_fenced_locked(conn);
}

// Bookkeeping for one mutating frame of a (possibly chunked) write
// sequence — the single place the open_writes invariant lives for
// BSET/BADD/BSTEP.  Construct AFTER locking the tensor: the offset-0
// frame opens the sequence.  Call fail(e) on any rejection (aborts the
// sequence, so a malformed or mismatched frame cannot wedge readers on
// a permanently-odd version), finish() after a successful mutation
// (closes the sequence on its final chunk and bumps the version).
struct SeqFrame {
  Tensor* t;
  ConnState* conn;
  const std::string& key;
  SeqFrame(Tensor* t, size_t off, ConnState* conn, const std::string& key)
      : t(t), conn(conn), key(key) {
    if (off == 0) {
      ++t->open_writes;
      conn->open_seqs.insert(key);
    }
  }
  std::string fail(const char* e) {
    if (t->open_writes > 0) --t->open_writes;
    conn->open_seqs.erase(key);
    return e;
  }
  void finish(bool final_chunk) {
    if (final_chunk) {
      if (t->open_writes > 0) --t->open_writes;
      conn->open_seqs.erase(key);
    }
    ++t->version;
  }
};

std::shared_ptr<Tensor> find_tensor(const std::string& key, bool create) {
  std::lock_guard<std::mutex> l(g_store.mu);
  auto it = g_store.tensors.find(key);
  if (it != g_store.tensors.end()) return it->second;
  if (!create) return nullptr;
  auto t = std::make_shared<Tensor>();
  g_store.tensors[key] = t;
  return t;
}

// A CONTINUATION frame (declared offset > 0) rejected before its tensor
// lock (bad payload / bad range) still aborts the sequence its writer
// opened at offset 0 — otherwise one malformed chunk would wedge the
// key's readers on a permanently-odd version until DELNS removes the
// tensor. Only continuation chunks qualify: an offset-0 (or offsetless,
// or unparsable-offset) frame rejected here never opened a sequence —
// SeqFrame is constructed after these checks — so decrementing for it
// would close ANOTHER writer's in-flight chunked sequence and clear the
// torn-read parity bit under that writer's feet. `off_declared` is the
// frame's raw declared offset (-1 when absent/unparsable).
std::string abort_open_seq(ConnState* conn, const std::string& key,
                           int64_t off_declared, const char* e) {
  if (off_declared <= 0) return e;
  conn->open_seqs.erase(key);
  std::shared_ptr<Tensor> t = find_tensor(key, /*create=*/false);
  if (t) {
    std::lock_guard<std::mutex> l(t->mu);
    if (t->open_writes > 0) --t->open_writes;
  }
  return e;
}

// Disconnect-time abort of every sequence the connection still holds
// open: a writer that died mid-chunked-push will never send the final
// chunk, and its readers must not stay wedged on odd parity until a
// DELNS. Same semantics as the per-frame aborts — release the
// open_writes slot, leave the (partial) data for the staleness model
// to absorb like any other bounded-lag contribution.
void abort_conn_seqs(ConnState* conn) {
  for (const std::string& key : conn->open_seqs) {
    std::shared_ptr<Tensor> t = find_tensor(key, /*create=*/false);
    if (!t) continue;
    std::lock_guard<std::mutex> l(t->mu);
    if (t->open_writes > 0) --t->open_writes;
  }
  conn->open_seqs.clear();
}

// Fencing re-check for the B* handlers, run AFTER taking the tensor
// lock (caller holds t->mu): the wire-entry is_fenced check alone is
// not enough — a fence bump landing between it and the tensor lock
// would let one in-flight zombie frame commit after its exclusion
// became observable. Inlines the sequence abort (abort_open_seq would
// re-lock t->mu): a fenced continuation chunk releases the open_writes
// slot its sequence holds so readers are not wedged on odd parity.
bool reject_fenced_under_tensor_lock(ConnState* conn,
                                     const std::string& key, Tensor* t,
                                     int64_t off_decl) {
  if (!is_fenced(*conn)) return false;
  if (off_decl > 0 && t->open_writes > 0) {
    --t->open_writes;
    conn->open_seqs.erase(key);
  }
  return true;
}

// The raw declared offset of a B* command's optional trailing
// `<off> <total>` range, parsed WITHOUT validation (the frame is
// already being rejected; this only decides whether it could have been
// a continuation chunk of an open sequence). -1 when absent or
// unparsable. Restores the stream position so read_range (in the
// accept path) is unaffected.
int64_t declared_offset(std::istringstream* in) {
  in->clear();   // a rangeless header leaves eofbit set from the parse
  std::streampos pos = in->tellg();
  int64_t o = -1;
  // parse the offset ALONE: a continuation frame whose total token is
  // corrupt ("5 garbage") must still abort its own open sequence
  if (!(*in >> o)) o = -1;
  in->clear();
  if (pos != std::streampos(-1)) in->seekg(pos);
  return o;
}

// -- sha256 / hmac (handshake) -----------------------------------------------
// Compact FIPS-180-4 SHA-256; no external crypto dependency.

struct Sha256 {
  uint32_t h[8];
  uint8_t buf[64];
  uint64_t len = 0;
  size_t fill = 0;

  Sha256() {
    static const uint32_t init[8] = {
        0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
        0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    memcpy(h, init, sizeof(h));
  }

  static uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }

  void block(const uint8_t* p) {
    static const uint32_t k[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; ++i)
      w[i] = (uint32_t(p[i * 4]) << 24) | (uint32_t(p[i * 4 + 1]) << 16) |
             (uint32_t(p[i * 4 + 2]) << 8) | uint32_t(p[i * 4 + 3]);
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^
                    (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^
                    (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
    uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + s1 + ch + k[i] + w[i];
      uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const void* data, size_t n) {
    const uint8_t* p = static_cast<const uint8_t*>(data);
    len += n;
    while (n) {
      size_t take = std::min(n, sizeof(buf) - fill);
      memcpy(buf + fill, p, take);
      fill += take; p += take; n -= take;
      if (fill == sizeof(buf)) { block(buf); fill = 0; }
    }
  }

  void final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (fill != 56) update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; ++i) lenb[i] = uint8_t(bits >> (56 - 8 * i));
    update(lenb, 8);
    for (int i = 0; i < 8; ++i) {
      out[i * 4] = uint8_t(h[i] >> 24);
      out[i * 4 + 1] = uint8_t(h[i] >> 16);
      out[i * 4 + 2] = uint8_t(h[i] >> 8);
      out[i * 4 + 3] = uint8_t(h[i]);
    }
  }
};

void hmac_sha256(const std::string& key, const std::string& msg,
                 uint8_t out[32]) {
  uint8_t k[64] = {0};
  if (key.size() > 64) {
    Sha256 s; s.update(key.data(), key.size()); s.final(k);
  } else {
    memcpy(k, key.data(), key.size());
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; ++i) { ipad[i] = k[i] ^ 0x36; opad[i] = k[i] ^ 0x5c; }
  uint8_t inner[32];
  Sha256 si; si.update(ipad, 64); si.update(msg.data(), msg.size());
  si.final(inner);
  Sha256 so; so.update(opad, 64); so.update(inner, 32); so.final(out);
}

std::string to_hex(const uint8_t* p, size_t n) {
  static const char* d = "0123456789abcdef";
  std::string out(n * 2, '0');
  for (size_t i = 0; i < n; ++i) {
    out[2 * i] = d[p[i] >> 4];
    out[2 * i + 1] = d[p[i] & 15];
  }
  return out;
}

// Returns 32 hex chars of OS entropy, or "" when no unpredictable
// source exists.  NO predictable fallback (ADVICE r4): a clock+pid
// nonce makes the HMAC challenge replayable, so the caller must fail
// closed (refuse the authenticated connection) on "".
std::string make_nonce() {
  uint8_t raw[16];
  size_t got = 0;
  FILE* f = fopen("/dev/urandom", "rb");
  if (f) {
    got = fread(raw, 1, sizeof(raw), f);
    fclose(f);
  }
  if (got != sizeof(raw)) {
    try {
      std::random_device rd;  // getrandom()/RDRAND-backed on Linux
      for (size_t i = 0; i < sizeof(raw); i += 4) {
        uint32_t v = rd();
        memcpy(raw + i, &v, sizeof(v));
      }
      got = sizeof(raw);
    } catch (...) {
      return "";
    }
  }
  return to_hex(raw, sizeof(raw));
}

bool constant_time_eq(const std::string& a, const std::string& b) {
  if (a.size() != b.size()) return false;
  unsigned char diff = 0;
  for (size_t i = 0; i < a.size(); ++i) diff |= a[i] ^ b[i];
  return diff == 0;
}

// -- wire dtypes -------------------------------------------------------------

// Branch-free (a select, not a branch) so the element loops in
// encode_wire/decode_wire auto-vectorize — the conversion competes
// with socket I/O for the same cores under multi-worker contention,
// where a scalar loop measurably cost more than the bf16 byte saving
// bought (BASELINE.md round-4 bf16 row, fixed round 5).
uint16_t f32_to_bf16(float f) {
  uint32_t u;
  memcpy(&u, &f, 4);
  // round-to-nearest-even, like XLA's f32->bf16 convert; NaN must not
  // round into Inf, so select the quieted-NaN form instead
  uint32_t bias = 0x7fff + ((u >> 16) & 1);
  uint16_t rtne = static_cast<uint16_t>((u + bias) >> 16);
  uint16_t qnan = static_cast<uint16_t>((u >> 16) | 0x0040);
  return (u & 0x7fffffffu) > 0x7f800000u ? qnan : rtne;
}

float bf16_to_f32(uint16_t h) {
  uint32_t u = static_cast<uint32_t>(h) << 16;
  float f;
  memcpy(&f, &u, 4);
  return f;
}

// Block size for i8 frames this service ENCODES (BGET replies); frames
// it decodes carry their own block size in the header. Read once: the
// env is fixed for the process lifetime, like the auth token.
size_t i8_encode_block() {
  static const size_t block = [] {
    const char* raw = getenv("AUTODIST_QUANT_BLOCK");
    long v = raw ? atol(raw) : 0;
    return v >= 8 ? static_cast<size_t>(v) : static_cast<size_t>(256);
  }();
  return block;
}

// wire "f32": payload is raw little-endian float32; "bf16": raw uint16
// upper halves of float32; "i8": blockscale frame `u32 block, u32 n,
// f32 scales x ceil(n/block), int8 q x n` (value = q * per-block
// scale). Returns false on a malformed payload.
bool decode_wire(std::string_view payload, const std::string& wire,
                 std::vector<float>* out) {
  if (wire == "f32") {
    if (payload.size() % 4) return false;
    out->resize(payload.size() / 4);
    memcpy(out->data(), payload.data(), payload.size());
    return true;
  }
  if (wire == "bf16") {
    if (payload.size() % 2) return false;
    size_t n = payload.size() / 2;
    out->resize(n);
    const uint16_t* src =
        reinterpret_cast<const uint16_t*>(payload.data());
    for (size_t i = 0; i < n; ++i) (*out)[i] = bf16_to_f32(src[i]);
    return true;
  }
  if (wire == "i8") {
    if (payload.size() < 8) return false;
    uint32_t block = 0, n = 0;
    memcpy(&block, payload.data(), 4);
    memcpy(&n, payload.data() + 4, 4);
    if (block == 0) return false;
    const size_t nb = (static_cast<size_t>(n) + block - 1) / block;
    if (payload.size() != 8 + nb * 4 + n) return false;
    std::vector<float> scales(nb);
    if (nb) memcpy(scales.data(), payload.data() + 8, nb * 4);
    const int8_t* q =
        reinterpret_cast<const int8_t*>(payload.data() + 8 + nb * 4);
    out->resize(n);
    // block-strided inner loop (contiguous, constant scale) so the
    // dequant auto-vectorizes like the bf16 path — same contention
    // lesson (BASELINE.md round-4 bf16 row)
    for (size_t b = 0; b < nb; ++b) {
      const float s = scales[b];
      const size_t lo = b * block;
      const size_t hi = std::min(lo + block, static_cast<size_t>(n));
      for (size_t i = lo; i < hi; ++i)
        (*out)[i] = static_cast<float>(q[i]) * s;
    }
    return true;
  }
  return false;
}

bool encode_wire(const float* v, size_t n, const std::string& wire,
                 std::string* out) {
  if (wire == "f32") {
    out->assign(reinterpret_cast<const char*>(v), n * 4);
    return true;
  }
  if (wire == "bf16") {
    out->resize(n * 2);
    uint16_t* dst = reinterpret_cast<uint16_t*>(&(*out)[0]);
    for (size_t i = 0; i < n; ++i) dst[i] = f32_to_bf16(v[i]);
    return true;
  }
  if (wire == "i8") {
    const size_t block = i8_encode_block();
    const size_t nb = (n + block - 1) / block;
    out->resize(8 + nb * 4 + n);
    char* raw = &(*out)[0];
    const uint32_t block32 = static_cast<uint32_t>(block);
    const uint32_t n32 = static_cast<uint32_t>(n);
    memcpy(raw, &block32, 4);
    memcpy(raw + 4, &n32, 4);
    float* scales = reinterpret_cast<float*>(raw + 8);
    int8_t* q = reinterpret_cast<int8_t*>(raw + 8 + nb * 4);
    for (size_t b = 0; b < nb; ++b) {
      const size_t lo = b * block;
      const size_t hi = std::min(lo + block, n);
      float maxabs = 0.f;
      for (size_t i = lo; i < hi; ++i)
        maxabs = std::max(maxabs, std::fabs(v[i]));
      // the +1e-30f epsilon matches the Python encoder exactly (an
      // all-zero block must not divide by zero); round-to-nearest +
      // clamp as branch-free min/max selects so the loop vectorizes
      const float scale = maxabs / 127.0f + 1e-30f;
      const float inv = 1.0f / scale;
      scales[b] = scale;
      for (size_t i = lo; i < hi; ++i) {
        float r = std::nearbyintf(v[i] * inv);
        r = std::max(-127.0f, std::min(127.0f, r));
        q[i] = static_cast<int8_t>(r);
      }
    }
    return true;
  }
  return false;
}

int64_t counter_of(const std::string& key) {
  auto it = g_store.counters.find(key);
  return it == g_store.counters.end() ? 0 : it->second;
}

// min over counters with the prefix; count reported via out param.
int64_t prefix_min(const std::string& prefix, int* count) {
  int64_t min_v = INT64_MAX;
  int n = 0;
  for (auto it = g_store.counters.lower_bound(prefix);
       it != g_store.counters.end() &&
       it->first.compare(0, prefix.size(), prefix) == 0;
       ++it) {
    ++n;
    if (it->second < min_v) min_v = it->second;
  }
  *count = n;
  return n ? min_v : 0;
}

template <typename M>
size_t erase_prefix(M* m, const std::string& prefix) {
  size_t n = 0;
  auto it = m->lower_bound(prefix);
  while (it != m->end() &&
         it->first.compare(0, prefix.size(), prefix) == 0) {
    it = m->erase(it);
    ++n;
  }
  return n;
}

// Payload bytes that follow the header line, or 0 for text commands;
// kBadPayload for an unparsable or over-cap declaration.
size_t payload_size(const std::string& line) {
  std::istringstream in(line);
  std::string cmd, key;
  in >> cmd;
  if (cmd == "BSADD") {
    // <nrows> int32 indices + <nrows> rows of <row_bytes> wire bytes;
    // guard the product against uint64 wraparound before comparing to
    // the cap (a wrapped declaration must not buffer toward 2^64).
    // i8 frames declare row_bytes as the TOTAL rows-blob length (the
    // blockscale scales header makes the blob non-row-divisible), so
    // the payload is indices + exactly that many bytes.
    uint64_t nrows = 0, row_bytes = 0;
    std::string wire;
    in >> key >> nrows >> row_bytes >> wire;
    if (in.fail() || row_bytes > kMaxPayload) return kBadPayload;
    if (wire == "i8") {
      if (nrows > kMaxPayload / 4 ||
          nrows * 4 > kMaxPayload - row_bytes)
        return kBadPayload;
      return static_cast<size_t>(nrows * 4 + row_bytes);
    }
    if (nrows > kMaxPayload / (4 + row_bytes)) return kBadPayload;
    uint64_t total = nrows * (4 + row_bytes);
    if (total > kMaxPayload) return kBadPayload;
    return static_cast<size_t>(total);
  }
  if (cmd == "BGETROWS") {
    uint64_t nrows = 0;
    in >> key >> nrows;
    if (in.fail() || nrows > kMaxPayload / 4) return kBadPayload;
    return static_cast<size_t>(nrows * 4);
  }
  if (cmd != "BSET" && cmd != "BADD" && cmd != "BSTEP") return 0;
  uint64_t nbytes = 0;
  in >> key >> nbytes;
  if (in.fail() || nbytes > kMaxPayload) return kBadPayload;
  return static_cast<size_t>(nbytes);
}

// Optional trailing `<off> <total>` range on a B* command; defaults to
// the whole tensor (off 0, total = payload elements). The declared
// total is capped like the payload itself (kMaxPayload bytes of f32) —
// an unvalidated total would let one malformed command allocate
// int64-max floats and bad_alloc the service.
bool read_range(std::istringstream* in, size_t n_elems, size_t* off,
                size_t* total) {
  constexpr int64_t kMaxElems =
      static_cast<int64_t>(kMaxPayload / sizeof(float));
  *off = 0;
  *total = n_elems;
  int64_t o = -1, t = -1;
  if (*in >> o >> t) {
    if (o < 0 || t < 0 || t > kMaxElems ||
        static_cast<size_t>(o) + n_elems > static_cast<size_t>(t))
      return false;
    *off = static_cast<size_t>(o);
    *total = static_cast<size_t>(t);
  }
  return true;
}

// Handles one request. `payload` holds the request's raw bytes (B*
// commands); a BGET reply's bytes land in `reply_payload` and follow the
// returned header line on the wire.
std::string handle(const std::string& line, std::string_view payload,
                   std::string* reply_payload, ConnState* conn) {
  std::istringstream in(line);
  std::string cmd;
  in >> cmd;
  using namespace std::chrono;
  if (cmd == "PING") return "PONG";
  if (cmd == "FENCE") {
    std::string k;
    int64_t gen = -1;
    in >> k >> gen;
    if (k.empty() || gen < 0) return "ERR bad fence";
    std::lock_guard<std::mutex> l(g_store.mu);
    auto it = g_store.counters.find(k);
    int64_t cur = it == g_store.counters.end() ? 0 : it->second;
    // a would-be writer whose generation is already superseded must
    // learn it at bind time, not at its first rejected write
    if (cur > gen) return kFencedErr;
    conn->fence_key = k;
    conn->fence_gen = gen;
    return "OK";
  }
  if (cmd == "SET") {
    std::string k, v;
    in >> k;
    std::getline(in, v);
    if (!v.empty() && v[0] == ' ') v.erase(0, 1);
    std::lock_guard<std::mutex> l(g_store.mu);
    if (is_fenced_locked(*conn)) return kFencedErr;
    g_store.kv[k] = v;
    g_store.cv.notify_all();
    return "OK";
  }
  if (cmd == "GET") {
    std::string k;
    in >> k;
    std::lock_guard<std::mutex> l(g_store.mu);
    auto it = g_store.kv.find(k);
    return it == g_store.kv.end() ? "NONE" : ("VAL " + it->second);
  }
  if (cmd == "DEL") {
    std::string k;
    in >> k;
    std::lock_guard<std::mutex> l(g_store.mu);
    // deletes are mutations: a fenced zombie erasing live keys (or a
    // whole namespace below) corrupts state as surely as a write
    if (is_fenced_locked(*conn)) return kFencedErr;
    g_store.kv.erase(k);
    g_store.counters.erase(k);
    return "OK";
  }
  if (cmd == "DELNS") {
    // run-end cleanup: a long-lived endpoint daemon must not accumulate
    // a dead run's multi-hundred-MB tensors (ADVICE r3)
    std::string prefix;
    in >> prefix;
    if (prefix.empty()) return "ERR empty prefix";
    std::lock_guard<std::mutex> l(g_store.mu);
    if (is_fenced_locked(*conn)) return kFencedErr;
    size_t n = erase_prefix(&g_store.kv, prefix);
    n += erase_prefix(&g_store.counters, prefix);
    n += erase_prefix(&g_store.tensors, prefix);
    n += erase_prefix(&g_store.barrier_arrivals, prefix);
    n += erase_prefix(&g_store.barrier_generation, prefix);
    g_store.cv.notify_all();
    return "VAL " + std::to_string(n);
  }
  if (cmd == "INCR") {
    std::string k;
    int64_t d = 1;
    in >> k >> d;
    std::lock_guard<std::mutex> l(g_store.mu);
    if (d != 0 && is_fenced_locked(*conn)) return kFencedErr;
    int64_t v = (g_store.counters[k] += d);
    g_store.cv.notify_all();
    return "VAL " + std::to_string(v);
  }
  if (cmd == "WAITGE") {
    std::string k;
    int64_t n = 0, ms = 0;
    in >> k >> n >> ms;
    std::unique_lock<std::mutex> l(g_store.mu);
    bool ok = g_store.cv.wait_for(l, milliseconds(ms), [&] {
      return counter_of(k) >= n || g_store.shutting_down;
    });
    if (!ok || g_store.shutting_down) return "TIMEOUT";
    return "VAL " + std::to_string(counter_of(k));
  }
  if (cmd == "MINWAIT") {
    std::string prefix;
    int64_t n = 0, k = 0, ms = 0;
    in >> prefix >> n >> k >> ms;
    std::unique_lock<std::mutex> l(g_store.mu);
    int count = 0;
    bool ok = g_store.cv.wait_for(l, milliseconds(ms), [&] {
      int c = 0;
      int64_t m = prefix_min(prefix, &c);
      return (c >= k && m >= n) || g_store.shutting_down;
    });
    if (!ok || g_store.shutting_down) return "TIMEOUT";
    return "VAL " + std::to_string(prefix_min(prefix, &count));
  }
  if (cmd == "BARRIER") {
    std::string name;
    int64_t k = 0, ms = 0;
    in >> name >> k >> ms;
    std::unique_lock<std::mutex> l(g_store.mu);
    int64_t gen = g_store.barrier_generation[name];
    int64_t arrived = ++g_store.barrier_arrivals[name];
    if (arrived >= k) {
      g_store.barrier_arrivals[name] = 0;
      ++g_store.barrier_generation[name];
      g_store.cv.notify_all();
      return "OK";
    }
    bool ok = g_store.cv.wait_for(l, milliseconds(ms), [&] {
      return g_store.barrier_generation[name] != gen ||
             g_store.shutting_down;
    });
    if (ok && !g_store.shutting_down) return "OK";
    // Withdraw this party's arrival so a timeout doesn't poison the
    // barrier name: a later round must still need k live arrivals. Only
    // if the round we joined never completed (generation unchanged).
    if (g_store.barrier_generation[name] == gen &&
        g_store.barrier_arrivals[name] > 0) {
      --g_store.barrier_arrivals[name];
    }
    return "TIMEOUT";
  }
  if (cmd == "BSET") {
    std::string k, wire;
    size_t nbytes = 0;
    in >> k >> nbytes >> wire;
    const int64_t off_decl = declared_offset(&in);
    // a writer fenced mid-sequence aborts the sequence it opened
    // (abort_open_seq) so its readers are not wedged on odd parity
    if (is_fenced(*conn)) return abort_open_seq(conn, k, off_decl, kFencedErr);
    std::vector<float> vals;
    if (!decode_wire(payload, wire, &vals))
      return abort_open_seq(conn, k, off_decl, "ERR bad payload");
    size_t off, total;
    if (!read_range(&in, vals.size(), &off, &total))
      return abort_open_seq(conn, k, off_decl, "ERR bad range");
    std::shared_ptr<Tensor> t = find_tensor(k, /*create=*/true);
    std::lock_guard<std::mutex> l(t->mu);
    if (reject_fenced_under_tensor_lock(conn, k, t.get(), off_decl))
      return kFencedErr;
    SeqFrame seq(t.get(), off, conn, k);
    if (off == 0) {  // a (re)set starts at its first chunk
      t->data.assign(total, 0.f);
      t->slot1.clear();
      t->slot2.clear();
      t->pushes = 0;
      t->steps = 0;
    }
    if (t->data.size() != total) return seq.fail("ERR shape mismatch");
    std::copy(vals.begin(), vals.end(), t->data.begin() + off);
    seq.finish(off + vals.size() >= total);
    return "OK";
  }
  if (cmd == "BSTAT") {
    // tensor introspection: pushes, optimizer steps, element count,
    // slot residency — lets tests/tools verify PS-resident optimizer
    // state (shared adam: steps == total pushes across workers)
    std::string k;
    in >> k;
    std::shared_ptr<Tensor> t = find_tensor(k, /*create=*/false);
    if (!t) return "NONE";
    std::lock_guard<std::mutex> l(t->mu);
    return "VAL " + std::to_string(t->pushes) + " " +
           std::to_string(t->steps) + " " +
           std::to_string(t->data.size()) + " " +
           std::to_string(t->slot1.empty() ? 0 : 1) + " " +
           std::to_string(t->slot2.empty() ? 0 : 1);
  }
  if (cmd == "BGET") {
    std::string k, wire;
    in >> k >> wire;
    if (wire.empty()) wire = "f32";
    // optional trailing "v" (after the optional range) opts in to a
    // version field in the reply — old clients keep the old format
    int64_t o = -1, c = -1;
    bool have_range = static_cast<bool>(in >> o >> c);
    in.clear();
    std::string flag;
    bool want_ver = static_cast<bool>(in >> flag) && flag == "v";
    std::shared_ptr<Tensor> t = find_tensor(k, /*create=*/false);
    if (!t) return "NONE";
    {
      std::lock_guard<std::mutex> l(t->mu);
      size_t off = 0, count = t->data.size();
      if (have_range) {
        if (o < 0 || c < 0 ||
            static_cast<size_t>(o) + static_cast<size_t>(c) >
                t->data.size())
          return "ERR bad range";
        off = static_cast<size_t>(o);
        count = static_cast<size_t>(c);
      }
      if (!encode_wire(t->data.data() + off, count, wire, reply_payload))
        return "ERR bad wire dtype";
      std::string resp = "VAL " + std::to_string(reply_payload->size());
      if (want_ver)
        resp += " " + std::to_string(t->version * 2 +
                                     (t->open_writes > 0 ? 1 : 0));
      return resp;
    }
  }
  if (cmd == "BADD") {
    std::string k, wire;
    size_t nbytes = 0;
    in >> k >> nbytes >> wire;
    const int64_t off_decl = declared_offset(&in);
    if (is_fenced(*conn)) return abort_open_seq(conn, k, off_decl, kFencedErr);
    std::vector<float> delta;
    if (!decode_wire(payload, wire, &delta))
      return abort_open_seq(conn, k, off_decl, "ERR bad payload");
    size_t off, total;
    if (!read_range(&in, delta.size(), &off, &total))
      return abort_open_seq(conn, k, off_decl, "ERR bad range");
    std::shared_ptr<Tensor> t = find_tensor(k, /*create=*/true);
    std::lock_guard<std::mutex> l(t->mu);
    if (reject_fenced_under_tensor_lock(conn, k, t.get(), off_decl))
      return kFencedErr;
    SeqFrame seq(t.get(), off, conn, k);
    if (t->data.empty()) t->data.assign(total, 0.f);
    if (t->data.size() != total) return seq.fail("ERR shape mismatch");
    if (off == 0) ++t->pushes;  // one logical push counts once
    for (size_t i = 0; i < delta.size(); ++i)
      t->data[off + i] += delta[i];
    seq.finish(off + delta.size() >= total);
    return "VAL " + std::to_string(t->pushes);
  }
  if (cmd == "BSADD") {
    // row-sparse scatter-add: the sparse sibling of BADD. Payload is
    // <nrows> little-endian int32 row indices followed by <nrows> rows
    // of wire data (row_bytes wire bytes each); every listed row is
    // added into the stored [rows, cols] tensor at its index. The
    // optional <off> <total> range counts ROWS of the logical push;
    // fencing / sequence-abort semantics are exactly BADD's.
    std::string k, wire;
    uint64_t nrows = 0, row_bytes = 0;
    in >> k >> nrows >> row_bytes >> wire;
    const int64_t off_decl = declared_offset(&in);
    if (is_fenced(*conn)) return abort_open_seq(conn, k, off_decl, kFencedErr);
    // i8 (blockscale) blobs are not per-row divisible: row_bytes is
    // the whole blob length and cols derives from decoded elements
    const bool i8 = wire == "i8";
    const size_t itemsize = wire == "bf16" ? 2 : 4;
    if (row_bytes == 0 || (!i8 && row_bytes % itemsize))
      return abort_open_seq(conn, k, off_decl, "ERR bad row bytes");
    if (payload.size() < nrows * 4)
      return abort_open_seq(conn, k, off_decl, "ERR bad payload");
    std::vector<int32_t> idx(nrows);
    if (nrows) memcpy(idx.data(), payload.data(), nrows * 4);
    std::vector<float> rows;
    if (!decode_wire(payload.substr(nrows * 4), wire, &rows))
      return abort_open_seq(conn, k, off_decl, "ERR bad payload");
    // i8 derives ncols from the decoded blob: an empty blob (n=0) with
    // nrows>0 would make ncols 0 and the shape-check modulo below a
    // division by zero (SIGFPE kills the whole service) — reject it
    // like BGETROWS rejects ncols==0
    if (i8 ? (nrows == 0 || rows.empty() || rows.size() % nrows)
           : rows.size() != nrows * (row_bytes / itemsize))
      return abort_open_seq(conn, k, off_decl, "ERR bad payload");
    const size_t ncols =
        i8 ? rows.size() / nrows : static_cast<size_t>(row_bytes) / itemsize;
    size_t off, total;
    if (!read_range(&in, static_cast<size_t>(nrows), &off, &total))
      return abort_open_seq(conn, k, off_decl, "ERR bad range");
    std::shared_ptr<Tensor> t = find_tensor(k, /*create=*/false);
    // unlike BADD, absence is an error: a row set cannot size the
    // dense tensor it scatters into
    if (!t) return abort_open_seq(conn, k, off_decl, "ERR no tensor");
    std::lock_guard<std::mutex> l(t->mu);
    if (reject_fenced_under_tensor_lock(conn, k, t.get(), off_decl))
      return kFencedErr;
    SeqFrame seq(t.get(), off, conn, k);
    if (t->data.empty() || t->data.size() % ncols)
      return seq.fail("ERR shape mismatch");
    const size_t table_rows = t->data.size() / ncols;
    for (uint64_t r = 0; r < nrows; ++r)
      if (idx[r] < 0 || static_cast<size_t>(idx[r]) >= table_rows)
        return seq.fail("ERR bad row index");
    if (off == 0) ++t->pushes;  // one logical push counts once
    for (uint64_t r = 0; r < nrows; ++r) {
      float* dst = t->data.data() + static_cast<size_t>(idx[r]) * ncols;
      const float* src = rows.data() + r * ncols;
      for (size_t j = 0; j < ncols; ++j) dst[j] += src[j];
    }
    seq.finish(off + nrows >= total);
    return "VAL " + std::to_string(t->pushes);
  }
  if (cmd == "BGETROWS") {
    // fetch just the rows listed in the int32 request payload — the
    // read half of the row-sparse plane (proxy-cache refresh after a
    // sparse push, pull-ahead of a known next batch). The torn-read
    // version contract matches BGET's "v" flag.
    std::string k, wire;
    uint64_t nrows = 0, ncols = 0;
    in >> k >> nrows >> ncols >> wire;
    if (wire.empty()) wire = "f32";
    std::string flag;
    bool want_ver = static_cast<bool>(in >> flag) && flag == "v";
    // bound the reply like every other buffer (kMaxPayload of f32):
    // an unvalidated nrows*ncols would let one request allocate
    // hundreds of GB (or wrap size_t) and bad_alloc the service
    constexpr uint64_t kMaxElems = kMaxPayload / sizeof(float);
    if (ncols == 0 || ncols > kMaxElems || nrows > kMaxElems / ncols)
      return "ERR reply too large";
    if (payload.size() < nrows * 4) return "ERR bad payload";
    std::vector<int32_t> idx(nrows);
    if (nrows) memcpy(idx.data(), payload.data(), nrows * 4);
    std::shared_ptr<Tensor> t = find_tensor(k, /*create=*/false);
    if (!t) return "NONE";
    std::lock_guard<std::mutex> l(t->mu);
    if (t->data.size() % ncols) return "ERR shape mismatch";
    const size_t table_rows = t->data.size() / ncols;
    std::vector<float> rows(static_cast<size_t>(nrows) * ncols);
    for (uint64_t r = 0; r < nrows; ++r) {
      if (idx[r] < 0 || static_cast<size_t>(idx[r]) >= table_rows)
        return "ERR bad row index";
      memcpy(rows.data() + r * ncols,
             t->data.data() + static_cast<size_t>(idx[r]) * ncols,
             ncols * sizeof(float));
    }
    if (!encode_wire(rows.data(), rows.size(), wire, reply_payload))
      return "ERR bad wire dtype";
    std::string resp = "VAL " + std::to_string(reply_payload->size());
    if (want_ver)
      resp += " " + std::to_string(t->version * 2 +
                                   (t->open_writes > 0 ? 1 : 0));
    return resp;
  }
  if (cmd == "BSTEP") {
    std::string k, wire, rule;
    size_t nbytes = 0;
    int64_t t_in = 0;
    double p0 = 0, p1 = 0, p2 = 0, p3 = 0;
    in >> k >> nbytes >> wire >> rule >> t_in >> p0 >> p1 >> p2 >> p3;
    const int64_t off_decl = declared_offset(&in);
    if (is_fenced(*conn)) return abort_open_seq(conn, k, off_decl, kFencedErr);
    std::vector<float> grad;
    if (!decode_wire(payload, wire, &grad))
      return abort_open_seq(conn, k, off_decl, "ERR bad payload");
    size_t off, total;
    if (!read_range(&in, grad.size(), &off, &total))
      return abort_open_seq(conn, k, off_decl, "ERR bad range");
    std::shared_ptr<Tensor> t = find_tensor(k, /*create=*/false);
    if (!t) return "ERR no tensor";
    std::lock_guard<std::mutex> l(t->mu);
    if (reject_fenced_under_tensor_lock(conn, k, t.get(), off_decl))
      return kFencedErr;
    SeqFrame seq(t.get(), off, conn, k);
    if (t->data.size() != total) return seq.fail("ERR shape mismatch");
    int64_t step = t_in;
    if (off == 0 && step == 0) step = ++t->steps;
    if (step <= 0) return seq.fail("ERR bad step");
    float* w = t->data.data() + off;
    const float* g = grad.data();
    const size_t n = grad.size();
    const float lr = static_cast<float>(p0);
    if (rule == "sgd") {
      const float m = static_cast<float>(p1);
      if (m != 0.f) {
        if (t->slot1.empty()) t->slot1.assign(total, 0.f);
        if (t->slot1.size() != total) return seq.fail("ERR slot mismatch");
        float* vel = t->slot1.data() + off;
        for (size_t i = 0; i < n; ++i) {
          vel[i] = m * vel[i] + g[i];
          w[i] -= lr * vel[i];
        }
      } else {
        for (size_t i = 0; i < n; ++i) w[i] -= lr * g[i];
      }
    } else if (rule == "adam") {
      const float b1 = static_cast<float>(p1);
      const float b2 = static_cast<float>(p2);
      const float eps = static_cast<float>(p3);
      if (t->slot1.empty()) t->slot1.assign(total, 0.f);
      if (t->slot2.empty()) t->slot2.assign(total, 0.f);
      if (t->slot1.size() != total || t->slot2.size() != total)
        return seq.fail("ERR slot mismatch");
      float* m = t->slot1.data() + off;
      float* v = t->slot2.data() + off;
      const float c1 =
          1.f - static_cast<float>(std::pow((double)b1, (double)step));
      const float c2 =
          1.f - static_cast<float>(std::pow((double)b2, (double)step));
      for (size_t i = 0; i < n; ++i) {
        m[i] = b1 * m[i] + (1.f - b1) * g[i];
        v[i] = b2 * v[i] + (1.f - b2) * g[i] * g[i];
        const float mhat = m[i] / c1;
        const float vhat = v[i] / c2;
        w[i] -= lr * mhat / (std::sqrt(vhat) + eps);
      }
    } else if (rule == "adagrad") {
      const float eps = static_cast<float>(p1);
      const float init_acc = static_cast<float>(p2);
      if (t->slot2.empty()) t->slot2.assign(total, init_acc);
      if (t->slot2.size() != total) return seq.fail("ERR slot mismatch");
      float* acc = t->slot2.data() + off;
      for (size_t i = 0; i < n; ++i) {
        acc[i] += g[i] * g[i];
        w[i] -= lr * g[i] / (std::sqrt(acc[i]) + eps);
      }
    } else {
      return seq.fail("ERR unknown rule");
    }
    seq.finish(off + grad.size() >= total);
    return "VAL " + std::to_string(step);
  }
  if (cmd == "SHUTDOWN") {
    std::lock_guard<std::mutex> l(g_store.mu);
    g_store.shutting_down = true;
    g_store.cv.notify_all();
    return "OK";
  }
  return "ERR unknown command";
}

bool send_all(int fd, const char* data, size_t len) {
  while (len) {
    ssize_t n = send(fd, data, len, 0);
    if (n <= 0) return false;
    data += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

// Reads the next newline-terminated header line into *line; false on EOF.
bool read_line(int fd, std::string* buf, std::string* line) {
  char chunk[1 << 16];
  size_t pos;
  while ((pos = buf->find('\n')) == std::string::npos) {
    ssize_t n = recv(fd, chunk, sizeof(chunk), 0);
    if (n <= 0) return false;
    buf->append(chunk, n);
  }
  *line = buf->substr(0, pos);
  buf->erase(0, pos + 1);
  if (!line->empty() && line->back() == '\r') line->pop_back();
  return true;
}

void serve_conn(int fd) {
  // TCP_NODELAY on every accepted connection: replies are written as
  // two send() calls (header line, then payload) — under Nagle the
  // payload segment waits for the client's ACK of the header, and the
  // client's delayed ACK turns EVERY payload-bearing reply (BGET and
  // friends) into a ~40ms stall on loopback. The client side has set
  // this since PR 1; the accept side was the missing half.
  {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }
  std::string buf;
  char chunk[1 << 16];
  ConnState conn;
  // fires on EVERY exit path: a connection that dies mid-chunked-write
  // (worker crash = recv failure/EOF) aborts the sequences it opened
  // instead of wedging their readers on odd parity forever
  struct SeqAborter {
    ConnState* c;
    ~SeqAborter() { abort_conn_seqs(c); }
  } seq_aborter{&conn};
  // greeting + handshake: with a token configured every connection must
  // answer the nonce challenge before its first real command
  {
    std::string nonce = g_token.empty() ? "" : make_nonce();
    if (!g_token.empty() && nonce.empty()) {
      // no entropy source: refuse rather than issue a replayable nonce
      const char* err = "ERR no entropy for auth nonce\n";
      send_all(fd, err, strlen(err));
      close(fd);
      return;
    }
    std::string hello =
        "HELLO " + (g_token.empty() ? std::string("open") : nonce) + "\n";
    if (!send_all(fd, hello.data(), hello.size())) {
      close(fd);
      return;
    }
    if (!g_token.empty()) {
      std::string line;
      if (!read_line(fd, &buf, &line)) {
        close(fd);
        return;
      }
      std::istringstream in(line);
      std::string cmd, mac;
      in >> cmd >> mac;
      uint8_t want[32];
      hmac_sha256(g_token, nonce, want);
      if (cmd != "AUTH" || !constant_time_eq(mac, to_hex(want, 32))) {
        const char* err = "ERR auth failed\n";
        send_all(fd, err, strlen(err));
        close(fd);
        return;
      }
      const char* ok = "OK\n";
      if (!send_all(fd, ok, strlen(ok))) {
        close(fd);
        return;
      }
    }
  }
  while (!g_store.shutting_down) {
    std::string line;
    if (!read_line(fd, &buf, &line)) {
      close(fd);
      return;
    }
    // then that command's declared payload bytes
    size_t need = payload_size(line);
    if (need == kBadPayload) {
      // refuse oversized/garbage declarations instead of buffering
      // toward them (ADVICE r3); the stream is now unframed, so close
      const char* err = "ERR payload too large\n";
      send_all(fd, err, strlen(err));
      close(fd);
      return;
    }
    while (buf.size() < need) {
      ssize_t n = recv(fd, chunk, sizeof(chunk), 0);
      if (n <= 0) {
        close(fd);
        return;
      }
      buf.append(chunk, n);
    }
    // zero-copy payload view into the connection buffer (a 100 MB push
    // used to pay a full substr copy here); handle() is synchronous,
    // and the buffer is erased only after it returns
    std::string_view payload(buf.data(), need);
    std::string reply_payload;
    std::string resp = handle(line, payload, &reply_payload, &conn) + "\n";
    buf.erase(0, need);
    if (!send_all(fd, resp.data(), resp.size()) ||
        (!reply_payload.empty() &&
         !send_all(fd, reply_payload.data(), reply_payload.size()))) {
      close(fd);
      return;
    }
    if (g_store.shutting_down) {  // reply sent; exit promptly —
      close(fd);                  // accept() would otherwise block
      _exit(0);
    }
  }
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  int port = argc > 1 ? atoi(argv[1]) : 14998;
  // Bind address: second arg; loopback unless the launcher asks for more
  // (multi-host runs pass 0.0.0.0 or the coordinator interface).
  const char* bind_addr = argc > 2 ? argv[2] : "127.0.0.1";
  // Shared secret from the environment (never argv: visible in ps);
  // multi-host launchers distribute it via the forwarded ENV set.
  const char* token = getenv("AUTODIST_COORD_TOKEN");
  if (token) g_token = token;
  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = inet_addr(bind_addr);
  addr.sin_port = htons(port);
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(srv, 128) != 0) {
    perror("listen");
    return 1;
  }
  fprintf(stderr, "coord_service listening on :%d (%s)\n", port,
          g_token.empty() ? "open" : "authenticated");
  fflush(stderr);
  std::vector<std::thread> threads;
  while (!g_store.shutting_down) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) break;
    threads.emplace_back(serve_conn, fd);
  }
  close(srv);
  for (auto& t : threads)
    if (t.joinable()) t.detach();
  return 0;
}
