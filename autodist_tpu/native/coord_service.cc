// Coordination service: TCP key/value + counters + barriers.
//
// TPU-native replacement for the control-plane primitives the reference
// gets from the TF C++ runtime (SURVEY.md §2.2): FIFO token queues for
// sync barriers and bounded staleness (ps_synchronizer.py:335-458) and
// the chief/worker rendezvous that tf.Server+grpc provided. SPMD
// collectives need none of this inside a program; this service covers the
// *between-program* coordination: multi-process barriers, bounded-
// staleness windows (each worker publishes its step; a worker may run
// ahead only while min_step >= my_step - staleness), heartbeats for
// fail-fast monitoring, and small metadata exchange (strategy ids).
//
// The tensor commands (VSET/VGET/VADD) are the PS data plane: the
// reference aggregates cross-worker gradients in ConditionalAccumulators
// living on the PS task (ps_synchronizer.py:556-633); here workers push
// float32 deltas with an atomic elementwise VADD into host memory —
// commutative apply-per-push, which is exactly the reference's
// staleness>0 accumulator mode (take_grad(1): every push is applied).
//
// Protocol: newline-terminated text commands over TCP.
//   SET <key> <value>            -> OK
//   GET <key>                    -> VAL <value> | NONE
//   DEL <key>                    -> OK
//   INCR <key> <delta>           -> VAL <n>        (atomic add, int64)
//   WAITGE <key> <n> <ms>        -> VAL <m> | TIMEOUT   (wait key >= n)
//   MINWAIT <prefix> <n> <k> <ms>-> VAL <min> | TIMEOUT
//       (wait until >=k keys share <prefix> and their min value >= n)
//   BARRIER <name> <k> <ms>      -> OK | TIMEOUT   (k-party barrier)
//   VSET <key> <b64>             -> OK   (store float32 tensor bytes)
//   VGET <key>                   -> VAL <b64> | NONE
//   VADD <key> <b64>             -> VAL <n>  (atomic elementwise += ;
//                                   creates the tensor if absent; returns
//                                   the tensor's accumulated push count)
//   PING                         -> PONG
//   SHUTDOWN                     -> OK (server exits)
//
// Build: g++ -O2 -std=c++17 -pthread -o coord_service coord_service.cc

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;
  std::map<std::string, int64_t> counters;
  std::map<std::string, int64_t> barrier_arrivals;
  std::map<std::string, int64_t> barrier_generation;
  std::map<std::string, std::vector<float>> tensors;
  std::map<std::string, int64_t> tensor_pushes;
  std::atomic<bool> shutting_down{false};
};

Store g_store;

// -- base64 (payloads for the tensor commands) ------------------------------

const char kB64[] =
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";

std::string b64_encode(const unsigned char* data, size_t len) {
  std::string out;
  out.reserve((len + 2) / 3 * 4);
  for (size_t i = 0; i < len; i += 3) {
    uint32_t v = data[i] << 16;
    if (i + 1 < len) v |= data[i + 1] << 8;
    if (i + 2 < len) v |= data[i + 2];
    out.push_back(kB64[(v >> 18) & 63]);
    out.push_back(kB64[(v >> 12) & 63]);
    out.push_back(i + 1 < len ? kB64[(v >> 6) & 63] : '=');
    out.push_back(i + 2 < len ? kB64[v & 63] : '=');
  }
  return out;
}

struct B64Rev {
  int rev[256];
  B64Rev() {
    for (int i = 0; i < 256; ++i) rev[i] = -1;
    for (int i = 0; i < 64; ++i) rev[static_cast<int>(kB64[i])] = i;
  }
};
// initialized before main(): connection threads share it read-only
const B64Rev g_b64rev;

bool b64_decode(const std::string& in, std::vector<unsigned char>* out) {
  const int* rev = g_b64rev.rev;
  out->clear();
  uint32_t v = 0;
  int bits = 0;
  for (char c : in) {
    if (c == '=') break;
    int d = rev[static_cast<unsigned char>(c)];
    if (d < 0) return false;
    v = (v << 6) | d;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out->push_back((v >> bits) & 0xff);
    }
  }
  return true;
}

int64_t counter_of(const std::string& key) {
  auto it = g_store.counters.find(key);
  return it == g_store.counters.end() ? 0 : it->second;
}

// min over counters with the prefix; count reported via out param.
int64_t prefix_min(const std::string& prefix, int* count) {
  int64_t min_v = INT64_MAX;
  int n = 0;
  for (auto it = g_store.counters.lower_bound(prefix);
       it != g_store.counters.end() &&
       it->first.compare(0, prefix.size(), prefix) == 0;
       ++it) {
    ++n;
    if (it->second < min_v) min_v = it->second;
  }
  *count = n;
  return n ? min_v : 0;
}

std::string handle(const std::string& line) {
  std::istringstream in(line);
  std::string cmd;
  in >> cmd;
  using namespace std::chrono;
  if (cmd == "PING") return "PONG";
  if (cmd == "SET") {
    std::string k, v;
    in >> k;
    std::getline(in, v);
    if (!v.empty() && v[0] == ' ') v.erase(0, 1);
    std::lock_guard<std::mutex> l(g_store.mu);
    g_store.kv[k] = v;
    g_store.cv.notify_all();
    return "OK";
  }
  if (cmd == "GET") {
    std::string k;
    in >> k;
    std::lock_guard<std::mutex> l(g_store.mu);
    auto it = g_store.kv.find(k);
    return it == g_store.kv.end() ? "NONE" : ("VAL " + it->second);
  }
  if (cmd == "DEL") {
    std::string k;
    in >> k;
    std::lock_guard<std::mutex> l(g_store.mu);
    g_store.kv.erase(k);
    g_store.counters.erase(k);
    return "OK";
  }
  if (cmd == "INCR") {
    std::string k;
    int64_t d = 1;
    in >> k >> d;
    std::lock_guard<std::mutex> l(g_store.mu);
    int64_t v = (g_store.counters[k] += d);
    g_store.cv.notify_all();
    return "VAL " + std::to_string(v);
  }
  if (cmd == "WAITGE") {
    std::string k;
    int64_t n = 0, ms = 0;
    in >> k >> n >> ms;
    std::unique_lock<std::mutex> l(g_store.mu);
    bool ok = g_store.cv.wait_for(l, milliseconds(ms), [&] {
      return counter_of(k) >= n || g_store.shutting_down;
    });
    if (!ok || g_store.shutting_down) return "TIMEOUT";
    return "VAL " + std::to_string(counter_of(k));
  }
  if (cmd == "MINWAIT") {
    std::string prefix;
    int64_t n = 0, k = 0, ms = 0;
    in >> prefix >> n >> k >> ms;
    std::unique_lock<std::mutex> l(g_store.mu);
    int count = 0;
    bool ok = g_store.cv.wait_for(l, milliseconds(ms), [&] {
      int c = 0;
      int64_t m = prefix_min(prefix, &c);
      return (c >= k && m >= n) || g_store.shutting_down;
    });
    if (!ok || g_store.shutting_down) return "TIMEOUT";
    return "VAL " + std::to_string(prefix_min(prefix, &count));
  }
  if (cmd == "BARRIER") {
    std::string name;
    int64_t k = 0, ms = 0;
    in >> name >> k >> ms;
    std::unique_lock<std::mutex> l(g_store.mu);
    int64_t gen = g_store.barrier_generation[name];
    int64_t arrived = ++g_store.barrier_arrivals[name];
    if (arrived >= k) {
      g_store.barrier_arrivals[name] = 0;
      ++g_store.barrier_generation[name];
      g_store.cv.notify_all();
      return "OK";
    }
    bool ok = g_store.cv.wait_for(l, milliseconds(ms), [&] {
      return g_store.barrier_generation[name] != gen ||
             g_store.shutting_down;
    });
    if (ok && !g_store.shutting_down) return "OK";
    // Withdraw this party's arrival so a timeout doesn't poison the
    // barrier name: a later round must still need k live arrivals. Only
    // if the round we joined never completed (generation unchanged).
    if (g_store.barrier_generation[name] == gen &&
        g_store.barrier_arrivals[name] > 0) {
      --g_store.barrier_arrivals[name];
    }
    return "TIMEOUT";
  }
  if (cmd == "VSET") {
    std::string k, b64;
    in >> k >> b64;
    std::vector<unsigned char> bytes;
    if (!b64_decode(b64, &bytes) || bytes.size() % sizeof(float) != 0)
      return "ERR bad payload";
    std::lock_guard<std::mutex> l(g_store.mu);
    std::vector<float>& t = g_store.tensors[k];
    t.assign(bytes.size() / sizeof(float), 0.f);
    memcpy(t.data(), bytes.data(), bytes.size());
    g_store.tensor_pushes[k] = 0;
    g_store.cv.notify_all();
    return "OK";
  }
  if (cmd == "VGET") {
    std::string k;
    in >> k;
    std::vector<float> snapshot;
    {
      std::lock_guard<std::mutex> l(g_store.mu);
      auto it = g_store.tensors.find(k);
      if (it == g_store.tensors.end()) return "NONE";
      snapshot = it->second;  // copy under lock, encode outside it
    }
    return "VAL " + b64_encode(
        reinterpret_cast<const unsigned char*>(snapshot.data()),
        snapshot.size() * sizeof(float));
  }
  if (cmd == "VADD") {
    std::string k, b64;
    in >> k >> b64;
    std::vector<unsigned char> bytes;
    if (!b64_decode(b64, &bytes) || bytes.size() % sizeof(float) != 0)
      return "ERR bad payload";
    size_t n = bytes.size() / sizeof(float);
    const float* delta = reinterpret_cast<const float*>(bytes.data());
    std::lock_guard<std::mutex> l(g_store.mu);
    std::vector<float>& t = g_store.tensors[k];
    if (t.empty()) t.assign(n, 0.f);
    if (t.size() != n) return "ERR shape mismatch";
    for (size_t i = 0; i < n; ++i) t[i] += delta[i];
    int64_t pushes = ++g_store.tensor_pushes[k];
    g_store.cv.notify_all();
    return "VAL " + std::to_string(pushes);
  }
  if (cmd == "SHUTDOWN") {
    std::lock_guard<std::mutex> l(g_store.mu);
    g_store.shutting_down = true;
    g_store.cv.notify_all();
    return "OK";
  }
  return "ERR unknown command";
}

void serve_conn(int fd) {
  std::string buf;
  char chunk[4096];
  while (!g_store.shutting_down) {
    ssize_t n = recv(fd, chunk, sizeof(chunk), 0);
    if (n <= 0) break;
    buf.append(chunk, n);
    size_t pos;
    while ((pos = buf.find('\n')) != std::string::npos) {
      std::string line = buf.substr(0, pos);
      buf.erase(0, pos + 1);
      if (!line.empty() && line.back() == '\r') line.pop_back();
      std::string resp = handle(line) + "\n";
      if (send(fd, resp.data(), resp.size(), 0) < 0) {
        close(fd);
        return;
      }
      if (g_store.shutting_down) {  // reply sent; exit promptly —
        close(fd);                  // accept() would otherwise block
        _exit(0);
      }
    }
  }
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  int port = argc > 1 ? atoi(argv[1]) : 14998;
  // Bind address: second arg; loopback unless the launcher asks for more
  // (multi-host runs pass 0.0.0.0 or the coordinator interface).
  const char* bind_addr = argc > 2 ? argv[2] : "127.0.0.1";
  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = inet_addr(bind_addr);
  addr.sin_port = htons(port);
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(srv, 128) != 0) {
    perror("listen");
    return 1;
  }
  fprintf(stderr, "coord_service listening on :%d\n", port);
  fflush(stderr);
  std::vector<std::thread> threads;
  while (!g_store.shutting_down) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) break;
    threads.emplace_back(serve_conn, fd);
  }
  close(srv);
  for (auto& t : threads)
    if (t.joinable()) t.detach();
  return 0;
}
