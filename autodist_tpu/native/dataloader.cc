// Threaded prefetching record loader (shared library, ctypes ABI).
//
// The reference delegates input pipelines to TF's C++ runtime (queues /
// iterators; SURVEY.md §2.2). The TPU rebuild ships its own native
// loader: fixed-size binary records (static shapes — XLA-friendly),
// reader threads prefetching into a bounded batch queue so host IO
// overlaps device steps, and deterministic seeded shuffling + host
// sharding (record index mod num_shards) for multi-host data
// parallelism.
//
// File format (ADTR1): 8-byte magic "ADTR1\0\0\0", int64 record_size
// (bytes), int64 num_records, then num_records * record_size bytes.
//
// ABI (extern "C"):
//   void* adl_create(const char** files, int nfiles, int64 record_size,
//                    int64 batch_records, int threads, int64 seed,
//                    int shuffle, int64 shard_id, int64 num_shards,
//                    int64 queue_cap);
//   int64 adl_next(void* h, char* out);   // blocks; fills batch_records *
//                                         // record_size bytes; returns
//                                         // records written or -1 on err
//   int64 adl_epoch(void* h);             // completed epochs so far
//   void  adl_destroy(void* h);
//
// Build: g++ -O2 -std=c++17 -pthread -shared -fPIC -o dataloader.so
//        dataloader.cc

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr char kMagic[8] = {'A', 'D', 'T', 'R', '1', 0, 0, 0};

struct RecordRef {
  int file;
  int64_t offset;  // byte offset of the record in the file
};

struct Loader {
  std::vector<std::string> files;
  int64_t record_size = 0;
  int64_t batch_records = 0;
  int64_t queue_cap = 4;
  bool shuffle = false;
  int64_t seed = 0;
  int64_t shard_id = 0, num_shards = 1;

  std::vector<RecordRef> index;  // this shard's records
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::deque<std::vector<char>> queue;
  std::vector<std::thread> workers;
  bool stop = false;
  int64_t epoch = 0;
  int64_t error = 0;

  ~Loader() {
    {
      std::lock_guard<std::mutex> l(mu);
      stop = true;
    }
    cv_put.notify_all();
    cv_get.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
  }
};

bool build_index(Loader* L) {
  int64_t global = 0;
  for (int fi = 0; fi < static_cast<int>(L->files.size()); ++fi) {
    FILE* f = fopen(L->files[fi].c_str(), "rb");
    if (!f) return false;
    char magic[8];
    int64_t rec_size = 0, n_rec = 0;
    if (fread(magic, 1, 8, f) != 8 || memcmp(magic, kMagic, 8) != 0 ||
        fread(&rec_size, 8, 1, f) != 1 || fread(&n_rec, 8, 1, f) != 1 ||
        rec_size != L->record_size) {
      fclose(f);
      return false;
    }
    for (int64_t r = 0; r < n_rec; ++r, ++global) {
      if (global % L->num_shards == L->shard_id) {
        L->index.push_back({fi, 24 + r * rec_size});
      }
    }
    fclose(f);
  }
  return !L->index.empty();
}

// Single producer thread: sequential permuted reads, batches pushed to
// the bounded queue. (One thread per loader keeps epoch/order semantics
// deterministic; parallelism comes from overlapping with device compute.
// For higher throughput, create several sharded loaders.)
void producer(Loader* L) {
  std::mt19937_64 rng(L->seed);
  std::vector<size_t> order(L->index.size());
  for (size_t i = 0; i < order.size(); ++i) order[i] = i;
  std::vector<FILE*> handles(L->files.size(), nullptr);
  size_t pos = 0;
  if (L->shuffle) std::shuffle(order.begin(), order.end(), rng);
  std::vector<char> batch;
  while (true) {
    batch.assign(L->batch_records * L->record_size, 0);
    for (int64_t b = 0; b < L->batch_records; ++b) {
      if (pos == order.size()) {
        pos = 0;
        {
          std::lock_guard<std::mutex> l(L->mu);
          ++L->epoch;
        }
        if (L->shuffle) std::shuffle(order.begin(), order.end(), rng);
      }
      const RecordRef& ref = L->index[order[pos++]];
      FILE*& f = handles[ref.file];
      if (!f) f = fopen(L->files[ref.file].c_str(), "rb");
      if (!f || fseek(f, ref.offset, SEEK_SET) != 0 ||
          fread(batch.data() + b * L->record_size, 1, L->record_size,
                f) != static_cast<size_t>(L->record_size)) {
        std::lock_guard<std::mutex> l(L->mu);
        L->error = 1;
        L->cv_get.notify_all();
        for (FILE* h : handles)
          if (h) fclose(h);
        return;
      }
    }
    std::unique_lock<std::mutex> l(L->mu);
    L->cv_put.wait(l, [L] {
      return L->stop ||
             L->queue.size() < static_cast<size_t>(L->queue_cap);
    });
    if (L->stop) break;
    L->queue.push_back(std::move(batch));
    L->cv_get.notify_one();
  }
  for (FILE* h : handles)
    if (h) fclose(h);
}

}  // namespace

extern "C" {

void* adl_create(const char** files, int nfiles, int64_t record_size,
                 int64_t batch_records, int threads, int64_t seed,
                 int shuffle, int64_t shard_id, int64_t num_shards,
                 int64_t queue_cap) {
  (void)threads;  // see producer() comment
  auto* L = new Loader();
  for (int i = 0; i < nfiles; ++i) L->files.emplace_back(files[i]);
  L->record_size = record_size;
  L->batch_records = batch_records;
  L->seed = seed;
  L->shuffle = shuffle != 0;
  L->shard_id = shard_id;
  L->num_shards = num_shards;
  L->queue_cap = queue_cap > 0 ? queue_cap : 4;
  if (!build_index(L)) {
    delete L;
    return nullptr;
  }
  L->workers.emplace_back(producer, L);
  return L;
}

int64_t adl_next(void* h, char* out) {
  auto* L = static_cast<Loader*>(h);
  std::vector<char> batch;
  {
    std::unique_lock<std::mutex> l(L->mu);
    L->cv_get.wait(l, [L] {
      return L->stop || L->error || !L->queue.empty();
    });
    if (L->error || L->stop) return -1;
    batch = std::move(L->queue.front());
    L->queue.pop_front();
    L->cv_put.notify_one();
  }
  memcpy(out, batch.data(), batch.size());
  return L->batch_records;
}

int64_t adl_epoch(void* h) {
  auto* L = static_cast<Loader*>(h);
  std::lock_guard<std::mutex> l(L->mu);
  return L->epoch;
}

void adl_destroy(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"
