"""Minimal functional module system for the model zoo.

Models are pytrees of plain ``jax.Array`` params plus a parallel metadata
tree of *logical axis names* consumed by the sharding compiler
(:mod:`autodist_tpu.parallel.axes`). No framework magic: ``init`` builds
the param dict, ``apply`` is a pure function, so every model composes with
``jit`` / ``shard_map`` / ``jax.grad`` directly. This replaces the
reference's reliance on captured TF graphs + Keras (SURVEY.md §7: the
capture shim is only needed for API parity, not for the compute path).

Conventions:
- ``param_defs()`` -> {name: ParamDef | Module} describes one module level.
- params are nested dicts mirroring that structure.
- ``axes()`` returns the same nesting with ``ParamDef.axes`` at leaves.
- compute dtype is configurable (bfloat16 by default on TPU-class runs);
  params stay float32 (master weights), cast at use.
"""
import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from autodist_tpu.parallel.axes import (constrain, current_mesh,
                                        live_mesh_axis, manual_axis)


def sharded_embedding_lookup(table, ids, axis):
    """Row gather from a table sharded along dim 0 over mesh axis ``axis``.

    Each shard takes the rows it owns (out-of-range rows fill with 0) and
    a psum over the axis assembles full rows: comm is O(batch*dim), vs the
    O(batch*vocab) one-hot matmul. Works both inside an already-manual
    region (explicit collectives) and under GSPMD (wrapped in a
    partial-manual shard_map over just the vocab axis)."""
    def masked(shard, ids_):
        size = shard.shape[0]
        local = ids_ - jax.lax.axis_index(axis) * size
        # negative indices would wrap (numpy semantics); send them out of
        # bounds high so mode='fill' zeroes them
        local = jnp.where(local >= 0, local, size)
        rows = jnp.take(shard, local, axis=0, mode='fill', fill_value=0)
        return jax.lax.psum(rows, axis)

    if manual_axis(axis):
        return masked(table, ids)
    try:
        in_auto_ctx = bool(jax.sharding.get_abstract_mesh().shape)
        partial_manual = hasattr(jax, 'shard_map')
    except AttributeError:   # older jax: no mesh-context introspection
        in_auto_ctx, partial_manual = False, False
    if in_auto_ctx or not partial_manual:
        # already inside a manual region where the vocab axis stays auto
        # (shardy rejects a nested shard_map re-entering those axes), or
        # a jax without partial-manual shard_map: fall back to the
        # one-hot matmul (partitions cleanly under GSPMD and runs on
        # the MXU).
        vocab = table.shape[0]
        oh = jax.nn.one_hot(ids, vocab, dtype=table.dtype)
        return oh @ table
    from jax.sharding import PartitionSpec as P

    from autodist_tpu.parallel.axes import shard_map_compat
    return shard_map_compat(
        masked, current_mesh(), (P(axis), P()), P(),
        axis_names={axis})(table, ids)


@dataclass
class ParamDef:
    shape: tuple
    axes: tuple            # logical axis names, len == len(shape)
    init: str = 'normal'   # normal | zeros | ones | fan_in
    scale: float = 0.02
    # False = a STATE leaf (e.g. BatchNorm running stats): lives in the
    # params tree for checkpoint/sharding purposes, but the optimizer
    # must not touch it — it advances via record_state_update instead.
    trainable: bool = True


class Module:
    """Base: generic init/axes tree walks over ``param_defs()``."""

    def param_defs(self):
        raise NotImplementedError

    def apply(self, params, *args, **kwargs):
        raise NotImplementedError

    def init(self, rng):
        defs = self.param_defs()
        keys = jax.random.split(rng, max(len(defs), 1))
        out = {}
        for k, (name, d) in zip(keys, sorted(defs.items())):
            out[name] = d.init(k) if isinstance(d, Module) \
                else _init_leaf(k, d)
        return out

    def axes(self):
        return {name: (d.axes() if isinstance(d, Module) else d.axes)
                for name, d in sorted(self.param_defs().items())}

    def trainable_mask(self):
        """Bool tree mirroring ``init``: False at state leaves."""
        return {name: (d.trainable_mask() if isinstance(d, Module)
                       else d.trainable)
                for name, d in sorted(self.param_defs().items())}

    def has_state(self):
        return not all(jax.tree.leaves(self.trainable_mask()))

    def __call__(self, params, *args, **kwargs):
        return self.apply(params, *args, **kwargs)


# ---------------------------------------------------------------------------
# Model state (BatchNorm running stats etc.)
#
# State leaves live in the params tree (so sharding/checkpointing need no
# second tree) but advance through a trace-time side channel: during the
# loss trace a collector is active, stateful modules call
# ``record_state_update(path, value)``, and the trainer folds the updates
# back into the non-trainable leaves INSTEAD of an optimizer step. Paths
# are assigned to module instances once per trainer (``assign_state_paths``),
# which requires stateful modules to be held as attributes (they are).
# ---------------------------------------------------------------------------
import threading as _threading

_MODEL_CTX = _threading.local()


class _StateCollector:
    def __init__(self, training):
        self.training = training
        self.updates = {}    # path tuple -> new value (tracer ok)


class model_mode:
    """Context: set training/eval mode and collect state updates during
    a (traced) forward. ``updates`` is populated at trace time."""

    def __init__(self, training=True):
        self._col = _StateCollector(training)

    @property
    def updates(self):
        return self._col.updates

    def __enter__(self):
        stack = getattr(_MODEL_CTX, 'stack', None)
        if stack is None:
            stack = _MODEL_CTX.stack = []
        stack.append(self._col)
        return self

    def __exit__(self, *exc):
        _MODEL_CTX.stack.pop()


def _collector():
    stack = getattr(_MODEL_CTX, 'stack', None)
    return stack[-1] if stack else None


def is_training():
    """True outside any model_mode context (benchmark semantics)."""
    col = _collector()
    return True if col is None else col.training


def record_state_update(module, name, value):
    """Record a new value for state leaf ``name`` of ``module`` (no-op
    when no collector is active, e.g. plain benchmark forwards)."""
    col = _collector()
    if col is None:
        return
    path = getattr(module, '_state_path', None)
    if path is None:
        raise ValueError(
            '%s has state but no assigned path — build it through a '
            'Trainer (assign_state_paths) to track running statistics'
            % type(module).__name__)
    col.updates[path + (name,)] = value


def assign_state_paths(module, prefix=(), _seen=None):
    """Walk the module tree ONCE, stamping each submodule with its param
    path so state updates can be folded back by position.

    Stateful modules must occupy exactly ONE tree position and run once
    per loss forward — a single stamped path cannot represent two
    positions, so sharing a stateful instance (e.g. one BatchNorm used
    twice) is rejected here rather than silently dropping updates.
    Stateless instances may be shared freely."""
    if _seen is None:
        _seen = set()
    if id(module) in _seen and module.has_state():
        raise ValueError(
            'stateful module %s appears at multiple tree positions '
            '(%s and %s); give each position its own instance so its '
            'running statistics have a unique home'
            % (type(module).__name__, module._state_path, prefix))
    _seen.add(id(module))
    module._state_path = prefix
    for name, d in module.param_defs().items():
        if isinstance(d, Module):
            assign_state_paths(d, prefix + (name,), _seen)


def apply_tree_updates(tree, updates):
    """Return a copy of ``tree`` with ``{path tuple: value}`` entries
    replaced (copy-on-write along each path; the input is untouched)."""
    out = dict(tree)
    for path, value in updates.items():
        node = out
        for key in path[:-1]:
            node[key] = dict(node[key])
            node = node[key]
        node[path[-1]] = value.astype(node[path[-1]].dtype)
    return out


def _init_leaf(rng, d):
    if d.init == 'zeros':
        return jnp.zeros(d.shape, jnp.float32)
    if d.init == 'ones':
        return jnp.ones(d.shape, jnp.float32)
    if d.init == 'fan_in':
        # fan-in = product of all non-output dims (for a dense (in, out)
        # kernel that is `in`; for a conv HWIO kernel it is h*w*in)
        if len(d.shape) > 1:
            fan_in = 1
            for s in d.shape[:-1]:
                fan_in *= s
        else:
            fan_in = max(d.shape[0], 1)
        std = 1.0 / math.sqrt(fan_in)
        return jax.random.normal(rng, d.shape, jnp.float32) * std
    return jax.random.normal(rng, d.shape, jnp.float32) * d.scale


class Sequential(Module):
    """Compose modules; params keyed layer_0, layer_1, ..."""

    def __init__(self, layers):
        self.layers = list(layers)

    def param_defs(self):
        return {'layer_%03d' % i: m for i, m in enumerate(self.layers)}

    def apply(self, params, x, **kw):
        for i, m in enumerate(self.layers):
            x = m.apply(params['layer_%03d' % i], x, **kw)
        return x


class Dense(Module):
    """y = x @ w + b with logical axes for the two matmul dims."""

    def __init__(self, in_dim, out_dim, in_axis='embed', out_axis='mlp',
                 use_bias=True, dtype=jnp.float32, name=None):
        self.in_dim, self.out_dim = in_dim, out_dim
        self.in_axis, self.out_axis = in_axis, out_axis
        self.use_bias = use_bias
        self.dtype = dtype

    def param_defs(self):
        d = {'kernel': ParamDef((self.in_dim, self.out_dim),
                                (self.in_axis, self.out_axis), 'fan_in')}
        if self.use_bias:
            d['bias'] = ParamDef((self.out_dim,), (self.out_axis,), 'zeros')
        return d

    def apply(self, params, x):
        w = params['kernel'].astype(self.dtype)
        y = x.astype(self.dtype) @ w
        if self.use_bias:
            y = y + params['bias'].astype(self.dtype)
        return y


class Embedding(Module):
    """Token embedding; vocab dim shardable (EP-lite of the reference's
    partitioned embeddings, partitioner.py:576-602)."""

    def __init__(self, vocab, dim, vocab_axis='vocab', dim_axis='embed',
                 dtype=jnp.float32):
        self.vocab, self.dim = vocab, dim
        self.vocab_axis, self.dim_axis = vocab_axis, dim_axis
        self.dtype = dtype

    def param_defs(self):
        return {'table': ParamDef((self.vocab, self.dim),
                                  (self.vocab_axis, self.dim_axis),
                                  'normal', 0.02)}

    def apply(self, params, ids):
        table = params['table'].astype(self.dtype)
        axis = live_mesh_axis(self.vocab_axis)
        if axis is not None:
            # Vocab-sharded table: masked local gather + psum, O(B*dim)
            # comm instead of the O(B*vocab) one-hot matmul (the sharded
            # analogue of the reference's embedding_lookup_v2 over
            # partitioned vars, partitioner.py:576-602). The backward pass
            # transposes to a per-shard scatter-add of only the rows each
            # shard owns — the sparse gradient path, compiled by XLA.
            return sharded_embedding_lookup(table, ids, axis)
        return jnp.take(table, ids, axis=0)

    def attend(self, params, x):
        """Tied-output logits: x @ table.T"""
        return x @ params['table'].astype(self.dtype).T


class LayerNorm(Module):
    def __init__(self, dim, axis_name='embed', eps=1e-6,
                 dtype=jnp.float32):
        self.dim, self.axis_name, self.eps = dim, axis_name, eps
        self.dtype = dtype

    def param_defs(self):
        return {'scale': ParamDef((self.dim,), (self.axis_name,), 'ones'),
                'bias': ParamDef((self.dim,), (self.axis_name,), 'zeros')}

    def apply(self, params, x):
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
        y = (x32 - mu) * jax.lax.rsqrt(var + self.eps)
        y = y * params['scale'] + params['bias']
        return y.astype(self.dtype)


class Mlp(Module):
    """Transformer MLP: Megatron column- then row-parallel pair."""

    def __init__(self, dim, hidden, dtype=jnp.float32, act=jax.nn.gelu):
        self.up = Dense(dim, hidden, 'embed', 'mlp', dtype=dtype)
        self.down = Dense(hidden, dim, 'mlp', 'embed', dtype=dtype)
        self.act = act

    def param_defs(self):
        return {'up': self.up, 'down': self.down}

    def apply(self, params, x):
        h = self.act(self.up.apply(params['up'], x))
        h = constrain(h, ('batch', 'seq', 'mlp'))
        return self.down.apply(params['down'], h)
