"""ImageNet CNN family: ResNet, VGG, DenseNet, Inception.

The reference's benchmark suite (examples/benchmark/imagenet.py;
BASELINE.md rows ResNet101/DenseNet121/InceptionV3/VGG16) rebuilt on the
functional module system. TPU-first choices: NHWC layout (native for TPU
convolutions), bfloat16 compute with float32 master weights and float32
batch-norm statistics, channels padded by construction to MXU-friendly
multiples in the standard configs.

BatchNorm note: training mode normalizes with batch statistics; running
mean/variance EMAs are carried as non-trainable state leaves in the
params tree and advance through the Trainer's state-update channel
(tf.layers ``moving_mean``/``moving_variance`` parity). Eval mode
(``Trainer.evaluate`` / ``model_mode(training=False)``) normalizes with
the running statistics. Plain forwards outside any mode context keep
batch-stat semantics (what the throughput benchmarks exercise).
"""
import jax
import jax.numpy as jnp

from autodist_tpu.models.core import Dense, Module, ParamDef


def _s2d_stem_enabled():
    """Opt-in gate for the space-to-depth stem transform
    (``AUTODIST_S2D_STEM=1``). Default OFF: the round-5 A/B measured it
    NEUTRAL on v5e for ResNet-101/DenseNet-121 and ~1% slower for
    InceptionV3 (BASELINE.md round-5 s2d section) — XLA's conv emitter
    already handles the narrow stem; the family's MFU gap lives in the
    wide mid-network convs, not the one stem conv (~0.5% of FLOPs)."""
    from autodist_tpu.const import ENV
    return ENV.AUTODIST_S2D_STEM.val


def _densenet_dus_enabled():
    """Opt-in gate for the DenseNet buffer/dynamic-update-slice block
    form (``AUTODIST_DENSENET_DUS=1``); see DenseNet._apply_dus."""
    from autodist_tpu.const import ENV
    return ENV.AUTODIST_DENSENET_DUS.val


def space_to_depth_conv(x, kernel, stride=2, padding='SAME'):
    """Stride-2 conv computed in space-to-depth form.

    The classic TPU stem trick (MLPerf ResNet): a k×k stride-2 conv on
    a narrow-channel input (C=3 pads to 128 MXU lanes, wasting ~97% of
    the systolic array's contraction dim) is numerically IDENTICAL to a
    ceil(k/2)×ceil(k/2) stride-1 conv on the 2×2-space-to-depth'd input
    (C→4C) with correspondingly rearranged weights — same dot products,
    4× wider contraction, 4× fewer input spatial positions. This is a
    graph-level rewrite: XLA still emits a plain convolution, no custom
    kernel, no layout pinning (the round-4 Pallas lesson).

    ``kernel`` is the ORIGINAL [kh, kw, C, O] weights (param shape
    unchanged — checkpoints and init are oblivious); stride must be 2
    (the stem case), padding 'SAME' or 'VALID'.
    """
    assert stride == 2 and padding in ('SAME', 'VALID')
    n, h, w, c = x.shape
    kh, kw, _, o = kernel.shape
    if padding == 'SAME':
        out_h, out_w = -(-h // 2), -(-w // 2)
        pl_h = max((out_h - 1) * 2 + kh - h, 0) // 2
        pl_w = max((out_w - 1) * 2 + kw - w, 0) // 2
    else:
        out_h, out_w = (h - kh) // 2 + 1, (w - kw) // 2 + 1
        pl_h = pl_w = 0
    # kernel zero-padded to even extents (zero taps read zero-padded
    # input — output unchanged); input padded (or cropped: VALID may
    # discard a tail row the strided windows never covered) so one
    # VALID pass covers exactly the original window set
    kh2, kw2 = -(-kh // 2) * 2, -(-kw // 2) * 2
    in_h, in_w = (out_h - 1) * 2 + kh2, (out_w - 1) * 2 + kw2
    if in_h - pl_h < h:
        x = x[:, :in_h - pl_h]
    if in_w - pl_w < w:
        x = x[:, :, :in_w - pl_w]
    x = jnp.pad(x, ((0, 0), (pl_h, max(in_h - x.shape[1] - pl_h, 0)),
                    (pl_w, max(in_w - x.shape[2] - pl_w, 0)), (0, 0)))
    k = jnp.pad(kernel, ((0, kh2 - kh), (0, kw2 - kw), (0, 0), (0, 0)))
    # space-to-depth both operands with matching block order
    x = x.reshape(n, in_h // 2, 2, in_w // 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
        n, in_h // 2, in_w // 2, 4 * c)
    k = k.reshape(kh2 // 2, 2, kw2 // 2, 2, c, o)
    k = k.transpose(0, 2, 1, 3, 4, 5).reshape(
        kh2 // 2, kw2 // 2, 4 * c, o)
    return jax.lax.conv_general_dilated(
        x, k, window_strides=(1, 1), padding='VALID',
        dimension_numbers=('NHWC', 'HWIO', 'NHWC'))


class Conv(Module):
    """NHWC conv, HWIO kernel."""

    def __init__(self, in_ch, out_ch, kernel=3, stride=1, padding='SAME',
                 use_bias=False, dtype=jnp.float32):
        self.in_ch, self.out_ch = in_ch, out_ch
        self.kernel = (kernel, kernel) if isinstance(kernel, int) \
            else tuple(kernel)
        self.stride = (stride, stride) if isinstance(stride, int) \
            else tuple(stride)
        self.padding = padding
        self.use_bias = use_bias
        self.dtype = dtype

    def param_defs(self):
        d = {'kernel': ParamDef(self.kernel + (self.in_ch, self.out_ch),
                                (None, None, None, None), 'fan_in')}
        if self.use_bias:
            d['bias'] = ParamDef((self.out_ch,), (None,), 'zeros')
        return d

    def apply(self, params, x):
        if (self.stride == (2, 2) and
                self.padding in ('SAME', 'VALID') and
                self.in_ch <= 4 and _s2d_stem_enabled()):
            # narrow-channel stride-2 stem: space-to-depth form (same
            # numbers, MXU-friendlier — see space_to_depth_conv)
            y = space_to_depth_conv(x.astype(self.dtype),
                                    params['kernel'].astype(self.dtype),
                                    padding=self.padding)
        else:
            y = jax.lax.conv_general_dilated(
                x.astype(self.dtype),
                params['kernel'].astype(self.dtype),
                window_strides=self.stride, padding=self.padding,
                dimension_numbers=('NHWC', 'HWIO', 'NHWC'))
        if self.use_bias:
            y = y + params['bias'].astype(self.dtype)
        return y


class BatchNorm(Module):
    """Batch normalization with running statistics.

    Training mode (the default outside any ``model_mode`` context —
    benchmark semantics) normalizes with batch statistics and, when a
    state collector is active, records EMA updates of mean/var into the
    non-trainable ``ema_mean``/``ema_var`` leaves (tf.layers
    ``moving_mean``/``moving_variance`` parity). Eval mode
    (``model_mode(training=False)``, used by ``Trainer.evaluate``)
    normalizes with the running statistics."""

    def __init__(self, ch, eps=1e-5, momentum=0.9, dtype=jnp.float32):
        self.ch, self.eps, self.dtype = ch, eps, dtype
        self.momentum = momentum

    def param_defs(self):
        return {'scale': ParamDef((self.ch,), (None,), 'ones'),
                'bias': ParamDef((self.ch,), (None,), 'zeros'),
                'ema_mean': ParamDef((self.ch,), (None,), 'zeros',
                                     trainable=False),
                'ema_var': ParamDef((self.ch,), (None,), 'ones',
                                    trainable=False)}

    def coeffs_from_moments(self, params, mean, m2):
        """Folded normalize+affine coefficients (a, b) from first/second
        raw moments — the moments may come from an XLA reduce over the
        activation OR from the fused conv kernel's epilogue sums
        (kernels/conv_bn.py), which cost zero extra HBM traffic.
        Records the EMA state updates (training mode)."""
        from autodist_tpu.models.core import record_state_update
        var = jnp.maximum(m2 - jnp.square(mean), 0.0)
        m = self.momentum
        record_state_update(
            self, 'ema_mean', m * params['ema_mean'] + (1 - m) * mean)
        record_state_update(
            self, 'ema_var', m * params['ema_var'] + (1 - m) * var)
        a = params['scale'] * jax.lax.rsqrt(var + self.eps)
        b = params['bias'] - mean * a
        return a, b

    def coeffs(self, params, x):
        """(a, b) such that the normalized output is ``x*a + b``."""
        from autodist_tpu.models.core import is_training
        if is_training():
            # fused-BN formulation: one pass of f32-ACCUMULATED moments
            # (E[x], E[x^2]); the f32 convert fuses into the reduces, so
            # no [B,H,W,C] f32 temporary hits HBM. (The profile shows
            # XLA emits these as multi-output reduce fusions already; a
            # custom variadic-reduce variant — kernels/batch_norm.py
            # moments() — measured neutral-to-slower, see apply().)
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=(0, 1, 2))
            m2 = jnp.mean(jnp.square(xf), axis=(0, 1, 2))
            return self.coeffs_from_moments(params, mean, m2)
        mean = params['ema_mean']
        var = params['ema_var']
        a = params['scale'] * jax.lax.rsqrt(var + self.eps)
        b = params['bias'] - mean * a
        return a, b

    def apply(self, params, x):
        # normalize+affine folded to one per-channel multiply-add: the
        # [C]-vector coefficients are computed in f32, the elementwise
        # pass over the activations reads and writes the model dtype
        # (bf16 on TPU).
        #
        # Round-4 measurement note: a fully hand-scheduled BN
        # (kernels/batch_norm.py batch_norm_train: variadic one-pass
        # moments + closed-form two-pass backward) was built and is
        # numerically exact, but benches SLIGHTLY SLOWER here (v5e
        # ResNet-101 train 180 ms vs 174 ms, fwd 66 vs 55) — the
        # per-op profile shows XLA already emits multi-output
        # reduce+elementwise fusions for this formulation (one pass
        # computing dbeta, dgamma AND dx), and the custom_vjp boundary
        # blocks some cross-op fusion. Kept as an opt-in building
        # block; this graph-level form stays the default.
        a, b = self.coeffs(params, x)
        y = x.astype(self.dtype) * a.astype(self.dtype) + \
            b.astype(self.dtype)
        return y


def max_pool(x, window=3, stride=2, padding='SAME'):
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, window, window, 1),
        (1, stride, stride, 1), padding)


def avg_pool(x, window, stride=1, padding='VALID'):
    s = jax.lax.reduce_window(
        x, 0., jax.lax.add, (1, window, window, 1),
        (1, stride, stride, 1), padding)
    return s / (window * window)


def global_avg_pool(x):
    return jnp.mean(x, axis=(1, 2))


def _fused_conv_enabled():
    """Fused-pointwise dispatch gate: '1' opts in to the Pallas
    conv+BN kernel (interpret mode on CPU — the test tier); default
    OFF. Measured on v5e (ResNet-101, batch 256): the kernel's MXU
    throughput is fine late-stage, but Pallas pins its operands to
    default tiled layouts, and the layout-conversion copies at every
    kernel boundary cost more than the saved BN passes (train step
    241 ms gated / 317 ms ungated vs 174 ms without the kernel; the
    per-op profile shows XLA already emits the BN statistics and
    backward as single multi-output fusions, so there was less to save
    than the fusion names suggested). Full measurement notes in
    BASELINE.md."""
    from autodist_tpu.const import ENV
    return ENV.AUTODIST_FUSED_CONV.val


def _fused_max_rows():
    """Row-count ceiling for the fused kernel (0 = no limit). Pallas
    forces default tiled layouts on its operands, so every kernel call
    pays layout-conversion copies at its boundaries; on the huge
    early-stage activations those copies outweigh the saved BN passes
    (measured on v5e), while late stages win. Tunable for benchmarking."""
    from autodist_tpu.const import ENV
    return ENV.AUTODIST_FUSED_CONV_MAX_ROWS.val


def _fused_pointwise_ok(conv, x):
    from autodist_tpu.kernels import conv_bn as cb
    if conv.kernel != (1, 1) or conv.use_bias:
        return False
    sh, sw = conv.stride
    if sh != sw:   # fused_pointwise subsamples both dims by one stride
        return False
    b, h, w, _ = x.shape
    h, w = -(-h // sh), -(-w // sw)
    rows = b * h * w
    limit = _fused_max_rows()
    if limit and rows > limit:
        return False
    return cb.supports(rows, conv.in_ch, conv.out_ch)


def _fold(y, a, b, dt, relu=False, add=None):
    """The deferred BN epilogue ``relu?(y*a + b (+ add))`` as one
    elementwise pass in the model dtype (single definition for every
    fused call site)."""
    out = y.astype(dt) * a.astype(dt) + b.astype(dt)
    if add is not None:
        out = out + add
    return jax.nn.relu(out) if relu else out


def _pointwise_raw_coeffs(conv, bn, conv_params, bn_params, x,
                          prologue=None):
    """Fused 1x1 conv via the Pallas kernel: RAW conv output + the
    FOLLOWING BatchNorm's folded (a, b). ``prologue=(scale, bias,
    relu?)`` is the PREVIOUS BN's fold, applied on the way into the
    MXU. Moments come from the kernel epilogue (training) or the EMAs
    (eval). Shared by ConvBn.raw_coeffs and DenseLayer (one place to
    fix the stats fold)."""
    from autodist_tpu.models.core import is_training
    from autodist_tpu.kernels.conv_bn import fused_pointwise
    training = is_training()
    kern = conv_params['kernel'].reshape(conv.in_ch, conv.out_ch)
    scale, bias, prelu = (None, None, False) if prologue is None \
        else prologue
    y, s1, s2 = fused_pointwise(
        x.astype(conv.dtype), kern, scale=scale, bias=bias,
        prologue_relu=prelu, want_stats=training,
        stride=conv.stride[0])
    if training:
        n = y.shape[0] * y.shape[1] * y.shape[2]
        a, b = bn.coeffs_from_moments(bn_params, s1 / n, s2 / n)
    else:
        a, b = bn.coeffs(bn_params, y)
    return y, (a, b)


class ConvBn(Module):
    """conv + BN + optional relu — the CNN workhorse."""

    def __init__(self, in_ch, out_ch, kernel=3, stride=1, relu=True,
                 padding='SAME', dtype=jnp.float32):
        self.conv = Conv(in_ch, out_ch, kernel, stride, padding,
                         dtype=dtype)
        self.bn = BatchNorm(out_ch, dtype=dtype)
        self.relu = relu

    def param_defs(self):
        return {'conv': self.conv, 'bn': self.bn}

    def apply(self, params, x):
        if _fused_conv_enabled() and _fused_pointwise_ok(self.conv, x):
            # standalone fused form: the BN stats come from the MXU
            # epilogue (no stats pass); normalize+relu is one
            # elementwise pass (XLA-fused)
            y, (a, b) = self.raw_coeffs(params, x)
            return _fold(y, a, b, self.conv.dtype, relu=self.relu)
        y = self.bn.apply(params['bn'],
                          self.conv.apply(params['conv'], x))
        return jax.nn.relu(y) if self.relu else y

    # -- fused (deferred-normalize) protocol ------------------------------
    # raw_coeffs returns the RAW conv output plus this BN's folded
    # (a, b): the caller applies ``relu?(y*a + b)`` itself — usually by
    # folding it into the NEXT conv's prologue, so the normalize pass
    # never touches HBM (kernels/conv_bn.py design note).
    def raw_coeffs(self, params, x, prologue=None):
        """``(y_raw, (a, b))``. ``prologue=(scale, bias, relu?)`` is the
        PREVIOUS BN's fold, applied to ``x`` on the way in. 1x1 convs
        ride the Pallas fused kernel (BN moments from the epilogue);
        others take the XLA conv + reduce path."""
        if _fused_pointwise_ok(self.conv, x):
            return _pointwise_raw_coeffs(self.conv, self.bn,
                                         params['conv'], params['bn'],
                                         x, prologue)
        if prologue is not None:
            scale, bias, prelu = prologue
            x = _fold(x, scale, bias, self.conv.dtype, relu=prelu)
        y = self.conv.apply(params['conv'], x)
        return y, self.bn.coeffs(params['bn'], y)


# ---------------------------------------------------------------------------
# ResNet (v1.5 bottleneck; resnet50/101/152)
# ---------------------------------------------------------------------------

class Bottleneck(Module):
    expansion = 4

    def __init__(self, in_ch, width, stride=1, dtype=jnp.float32):
        out_ch = width * self.expansion
        self.a = ConvBn(in_ch, width, 1, 1, dtype=dtype)
        self.b = ConvBn(width, width, 3, stride, dtype=dtype)
        self.c = ConvBn(width, out_ch, 1, 1, relu=False, dtype=dtype)
        self.proj = None
        if stride != 1 or in_ch != out_ch:
            self.proj = ConvBn(in_ch, out_ch, 1, stride, relu=False,
                               dtype=dtype)
        self.out_ch = out_ch

    def param_defs(self):
        d = {'a': self.a, 'b': self.b, 'c': self.c}
        if self.proj is not None:
            d['proj'] = self.proj
        return d

    def apply(self, params, x):
        if _fused_conv_enabled() and \
                _fused_pointwise_ok(self.a.conv, x):
            return self._apply_fused(params, x)
        sc = x if self.proj is None else self.proj.apply(params['proj'], x)
        y = self.a.apply(params['a'], x)
        y = self.b.apply(params['b'], y)
        y = self.c.apply(params['c'], y)
        return jax.nn.relu(y + sc)

    def _apply_fused(self, params, x):
        """Bandwidth-lean bottleneck (kernels/conv_bn.py): the two 1x1
        convs ride the Pallas fused kernel — their BN moments come from
        the MXU epilogue (no stats pass over the activations) and bn2's
        normalize+ReLU folds into conv-c's prologue (no apply pass).
        Remaining full-tensor passes: bn1 apply into the 3x3's input,
        bn2's stats reduce, and ONE residual-add epilogue."""
        dt = self.a.conv.dtype
        y1, (a1, b1) = self.a.raw_coeffs(params['a'], x)
        y1n = _fold(y1, a1, b1, dt, relu=True)
        y2, (a2, b2) = self.b.raw_coeffs(params['b'], y1n)
        y3, (a3, b3) = self.c.raw_coeffs(params['c'], y2,
                                         prologue=(a2, b2, True))
        if self.proj is None:
            sc = x.astype(dt)
        else:
            ysc, (asc, bsc) = self.proj.raw_coeffs(params['proj'], x)
            sc = _fold(ysc, asc, bsc, dt)
        return _fold(y3, a3, b3, dt, relu=True, add=sc)


class ResNet(Module):
    """ResNet-v1.5; stage_sizes (3,4,23,3) = ResNet-101."""

    def __init__(self, stage_sizes, num_classes=1000, dtype=jnp.float32):
        self.stem = ConvBn(3, 64, 7, 2, dtype=dtype)
        self.blocks = []
        in_ch = 64
        for stage, n in enumerate(stage_sizes):
            width = 64 * (2 ** stage)
            for i in range(n):
                stride = 2 if (i == 0 and stage > 0) else 1
                blk = Bottleneck(in_ch, width, stride, dtype=dtype)
                self.blocks.append(blk)
                in_ch = blk.out_ch
        self.head = Dense(in_ch, num_classes, 'embed', 'classes',
                          dtype=dtype)

    @classmethod
    def resnet50(cls, **kw):
        return cls((3, 4, 6, 3), **kw)

    @classmethod
    def resnet101(cls, **kw):
        return cls((3, 4, 23, 3), **kw)

    @classmethod
    def resnet152(cls, **kw):
        return cls((3, 8, 36, 3), **kw)

    def param_defs(self):
        d = {'stem': self.stem, 'head': self.head}
        for i, b in enumerate(self.blocks):
            d['block_%03d' % i] = b
        return d

    def apply(self, params, x):
        y = self.stem.apply(params['stem'], x)
        y = max_pool(y, 3, 2)
        for i, b in enumerate(self.blocks):
            y = b.apply(params['block_%03d' % i], y)
        y = global_avg_pool(y)
        return self.head.apply(params['head'], y).astype(jnp.float32)

    def loss(self, params, batch):
        logits = self.apply(params, batch['images'])
        return _softmax_xent(logits, batch['labels'])


# ---------------------------------------------------------------------------
# VGG16
# ---------------------------------------------------------------------------

class VGG(Module):
    CFG16 = (64, 64, 'M', 128, 128, 'M', 256, 256, 256, 'M',
             512, 512, 512, 'M', 512, 512, 512, 'M')

    def __init__(self, cfg=CFG16, num_classes=1000, dtype=jnp.float32,
                 fc_spatial=7):
        """``fc_spatial`` is the spatial size after the conv stack
        (7 for CFG16 at 224px); the classic fixed-size fc head is sized
        from it, so custom cfgs/resolutions must pass theirs."""
        self.cfg = cfg
        self.fc_spatial = fc_spatial
        self.convs = []
        in_ch = 3
        for v in cfg:
            if v == 'M':
                continue
            self.convs.append(Conv(in_ch, v, 3, 1, use_bias=True,
                                   dtype=dtype))
            in_ch = v
        self.fc1 = Dense(in_ch * fc_spatial * fc_spatial, 4096,
                         'embed', 'mlp', dtype=dtype)
        self.fc2 = Dense(4096, 4096, 'mlp', 'mlp', dtype=dtype)
        self.head = Dense(4096, num_classes, 'mlp', 'classes',
                          dtype=dtype)

    @classmethod
    def vgg16(cls, **kw):
        return cls(cls.CFG16, **kw)

    def param_defs(self):
        d = {'fc1': self.fc1, 'fc2': self.fc2, 'head': self.head}
        for i, c in enumerate(self.convs):
            d['conv_%02d' % i] = c
        return d

    def apply(self, params, x):
        ci = 0
        y = x
        for v in self.cfg:
            if v == 'M':
                y = max_pool(y, 2, 2)
            else:
                y = jax.nn.relu(
                    self.convs[ci].apply(params['conv_%02d' % ci], y))
                ci += 1
        if y.shape[1] != self.fc_spatial:
            raise ValueError(
                'VGG conv stack produced %dx%d spatial but the fc head '
                'was sized for %dx%d; pass fc_spatial=%d for this '
                'cfg/resolution' % (y.shape[1], y.shape[2],
                                    self.fc_spatial, self.fc_spatial,
                                    y.shape[1]))
        y = y.reshape(y.shape[0], -1)
        y = jax.nn.relu(self.fc1.apply(params['fc1'], y))
        y = jax.nn.relu(self.fc2.apply(params['fc2'], y))
        return self.head.apply(params['head'], y).astype(jnp.float32)

    def loss(self, params, batch):
        logits = self.apply(params, batch['images'])
        return _softmax_xent(logits, batch['labels'])


# ---------------------------------------------------------------------------
# DenseNet121
# ---------------------------------------------------------------------------

class DenseLayer(Module):
    def __init__(self, in_ch, growth, dtype=jnp.float32):
        self.bn1 = BatchNorm(in_ch, dtype=dtype)
        self.conv1 = Conv(in_ch, 4 * growth, 1, dtype=dtype)
        self.bn2 = BatchNorm(4 * growth, dtype=dtype)
        self.conv2 = Conv(4 * growth, growth, 3, dtype=dtype)

    def param_defs(self):
        return {'bn1': self.bn1, 'conv1': self.conv1,
                'bn2': self.bn2, 'conv2': self.conv2}

    def growth_out(self, params, x):
        """The layer's NEW features only ([..., growth] — no concat):
        the caller decides how to append them (concat, or a
        dynamic-update-slice into a preallocated block buffer)."""
        if _fused_conv_enabled() and _fused_pointwise_ok(self.conv1, x):
            dt = self.conv1.dtype
            a1, b1 = self.bn1.coeffs(params['bn1'], x)
            y, (a2, b2) = _pointwise_raw_coeffs(
                self.conv1, self.bn2, params['conv1'], params['bn2'], x,
                prologue=(a1, b1, True))
            yn = _fold(y, a2, b2, dt, relu=True)
            return self.conv2.apply(params['conv2'], yn)
        y = self.conv1.apply(params['conv1'], jax.nn.relu(
            self.bn1.apply(params['bn1'], x)))
        return self.conv2.apply(params['conv2'], jax.nn.relu(
            self.bn2.apply(params['bn2'], y)))

    def apply(self, params, x):
        return jnp.concatenate([x, self.growth_out(params, x)],
                               axis=-1)


class DenseNet(Module):
    """DenseNet-BC; block config (6,12,24,16) = DenseNet-121."""

    def __init__(self, block_cfg=(6, 12, 24, 16), growth=32,
                 num_classes=1000, dtype=jnp.float32):
        self.stem = ConvBn(3, 2 * growth, 7, 2, dtype=dtype)
        ch = 2 * growth
        self.layers = []   # list of ('dense', layer) / ('trans', conv)
        for bi, n in enumerate(block_cfg):
            for _ in range(n):
                self.layers.append(('dense', DenseLayer(ch, growth,
                                                        dtype=dtype)))
                ch += growth
            if bi != len(block_cfg) - 1:
                self.layers.append(
                    ('trans', ConvBn(ch, ch // 2, 1, dtype=dtype)))
                ch //= 2
        self.bn_f = BatchNorm(ch, dtype=dtype)
        self.head = Dense(ch, num_classes, 'embed', 'classes',
                          dtype=dtype)

    @classmethod
    def densenet121(cls, **kw):
        return cls((6, 12, 24, 16), **kw)

    def param_defs(self):
        d = {'stem': self.stem, 'bn_f': self.bn_f, 'head': self.head}
        for i, (_, m) in enumerate(self.layers):
            d['layer_%03d' % i] = m
        return d

    def apply(self, params, x):
        y = self.stem.apply(params['stem'], x)
        y = max_pool(y, 3, 2)
        if _densenet_dus_enabled():
            return self._apply_dus(params, y)
        for i, (kind, m) in enumerate(self.layers):
            y = m.apply(params['layer_%03d' % i], y)
            if kind == 'trans':
                y = avg_pool(y, 2, 2, 'VALID')
        y = jax.nn.relu(self.bn_f.apply(params['bn_f'], y))
        y = global_avg_pool(y)
        return self.head.apply(params['head'], y).astype(jnp.float32)

    def _apply_dus(self, params, y):
        """Dense blocks via a preallocated buffer + dynamic-update-slice
        (AUTODIST_DENSENET_DUS=1): per layer only the ``growth`` new
        channels are WRITTEN, where the concat form rewrites the whole
        accumulated feature map — O(L) vs O(L^2) copy traffic per
        block. Numerically identical (buffer[..., :ch] == the concat
        prefix at every step; reads are unavoidable either way)."""
        i = 0
        n = len(self.layers)
        while i < n:
            kind, m = self.layers[i]
            if kind == 'trans':
                y = m.apply(params['layer_%03d' % i], y)
                y = avg_pool(y, 2, 2, 'VALID')
                i += 1
                continue
            # a run of dense layers: preallocate the block's final width
            run = 0
            while i + run < n and self.layers[i + run][0] == 'dense':
                run += 1
            ch = y.shape[-1]
            growth = self.layers[i][1].conv2.out_ch
            # the buffer is sized from the FIRST layer's growth; a
            # heterogeneous-growth block would silently clamp later
            # layers' writes into a too-small buffer — refuse instead
            growths = [self.layers[i + j][1].conv2.out_ch
                       for j in range(run)]
            if any(g != growth for g in growths):
                raise ValueError(
                    'AUTODIST_DENSENET_DUS requires every dense layer '
                    'in a block to share conv2.out_ch (growth); got %s '
                    'for layers %d..%d — use the concat form for '
                    'heterogeneous growth' % (growths, i, i + run - 1))
            buf = jnp.zeros(y.shape[:-1] + (ch + growth * run,),
                            y.dtype)
            buf = jax.lax.dynamic_update_slice_in_dim(
                buf, y, 0, axis=-1)
            for j in range(run):
                _, layer = self.layers[i + j]
                x_in = jax.lax.slice_in_dim(buf, 0, ch, axis=-1)
                new = layer.growth_out(
                    params['layer_%03d' % (i + j)], x_in)
                buf = jax.lax.dynamic_update_slice_in_dim(
                    buf, new.astype(buf.dtype), ch, axis=-1)
                ch += growth
            y = buf
            i += run
        y = jax.nn.relu(self.bn_f.apply(params['bn_f'], y))
        y = global_avg_pool(y)
        return self.head.apply(params['head'], y).astype(jnp.float32)

    def loss(self, params, batch):
        logits = self.apply(params, batch['images'])
        return _softmax_xent(logits, batch['labels'])


# ---------------------------------------------------------------------------
# InceptionV3 (faithful block structure, standard 299x299 stem)
# ---------------------------------------------------------------------------

class InceptionBlock(Module):
    """Generic inception block: parallel towers concatenated on channels.

    Each tower is a list of ConvBn specs (out_ch, kernel, stride,
    padding); ``pool`` adds an avg-pool+1x1 tower.
    """

    def __init__(self, in_ch, towers, pool_ch=0, dtype=jnp.float32):
        self.towers = []
        for tower in towers:
            mods, ch = [], in_ch
            for (out_ch, kernel, stride, padding) in tower:
                mods.append(ConvBn(ch, out_ch, kernel, stride,
                                   padding=padding, dtype=dtype))
                ch = out_ch
            self.towers.append(mods)
        self.pool_proj = ConvBn(in_ch, pool_ch, 1, dtype=dtype) \
            if pool_ch else None
        self.out_ch = sum(t[-1][0] for t in towers) + pool_ch

    def param_defs(self):
        d = {}
        for ti, mods in enumerate(self.towers):
            for mi, m in enumerate(mods):
                d['t%d_%d' % (ti, mi)] = m
        if self.pool_proj is not None:
            d['pool'] = self.pool_proj
        return d

    def apply(self, params, x):
        outs = []
        for ti, mods in enumerate(self.towers):
            y = x
            for mi, m in enumerate(mods):
                y = m.apply(params['t%d_%d' % (ti, mi)], y)
            outs.append(y)
        if self.pool_proj is not None:
            p = avg_pool(x, 3, 1, 'SAME')
            outs.append(self.pool_proj.apply(params['pool'], p))
        return jnp.concatenate(outs, axis=-1)


def _c(out, k=1, s=1, p='SAME'):
    return (out, k, s, p)


class InceptionV3(Module):
    def __init__(self, num_classes=1000, dtype=jnp.float32):
        d = dtype
        self.stem = [ConvBn(3, 32, 3, 2, padding='VALID', dtype=d),
                     ConvBn(32, 32, 3, 1, padding='VALID', dtype=d),
                     ConvBn(32, 64, 3, 1, dtype=d),
                     ConvBn(64, 80, 1, 1, padding='VALID', dtype=d),
                     ConvBn(80, 192, 3, 1, padding='VALID', dtype=d)]
        blocks = []
        ch = 192
        for pool_ch in (32, 64, 64):  # 3x inception-A
            b = InceptionBlock(ch, [[_c(64)],
                                    [_c(48), _c(64, 5)],
                                    [_c(64), _c(96, 3), _c(96, 3)]],
                               pool_ch, dtype=d)
            blocks.append(('b', b))
            ch = b.out_ch
        grid = InceptionBlock(ch, [[_c(384, 3, 2, 'VALID')],
                                   [_c(64), _c(96, 3),
                                    _c(96, 3, 2, 'VALID')]], 0, dtype=d)
        blocks.append(('g', grid))
        ch = grid.out_ch + ch  # pool branch concat keeps input channels
        for mid in (128, 160, 160, 192):  # 4x inception-B (7x1/1x7)
            b = InceptionBlock(
                ch, [[_c(192)],
                     [_c(mid), _c(mid, (1, 7)), _c(192, (7, 1))],
                     [_c(mid), _c(mid, (7, 1)), _c(mid, (1, 7)),
                      _c(mid, (7, 1)), _c(192, (1, 7))]],
                192, dtype=d)
            blocks.append(('b', b))
            ch = b.out_ch
        grid2 = InceptionBlock(ch, [[_c(192), _c(320, 3, 2, 'VALID')],
                                    [_c(192), _c(192, (1, 7)),
                                     _c(192, (7, 1)),
                                     _c(192, 3, 2, 'VALID')]], 0, dtype=d)
        blocks.append(('g', grid2))
        ch = grid2.out_ch + ch
        for _ in range(2):  # 2x inception-C
            b = InceptionBlock(ch, [[_c(320)],
                                    [_c(384), _c(384, (1, 3))],
                                    [_c(448), _c(384, 3), _c(384, (3, 1))]],
                               192, dtype=d)
            blocks.append(('b', b))
            ch = b.out_ch
        self.blocks = blocks
        self.head = Dense(ch, num_classes, 'embed', 'classes', dtype=d)

    def param_defs(self):
        d = {'head': self.head}
        for i, m in enumerate(self.stem):
            d['stem_%d' % i] = m
        for i, (_, m) in enumerate(self.blocks):
            d['inc_%02d' % i] = m
        return d

    def apply(self, params, x):
        if x.shape[1] < 75 or x.shape[2] < 75:
            # below this the grid reductions hit zero spatial size and
            # reductions over empty windows would silently produce NaN
            raise ValueError('InceptionV3 needs inputs >= 75x75, got '
                             '%dx%d' % (x.shape[1], x.shape[2]))
        y = x
        for i, m in enumerate(self.stem):
            y = m.apply(params['stem_%d' % i], y)
            if i == 2:
                y = max_pool(y, 3, 2, 'VALID')
        y = max_pool(y, 3, 2, 'VALID')
        for i, (kind, m) in enumerate(self.blocks):
            if kind == 'g':
                pooled = max_pool(y, 3, 2, 'VALID')
                y = jnp.concatenate([m.apply(params['inc_%02d' % i], y),
                                     pooled], axis=-1)
            else:
                y = m.apply(params['inc_%02d' % i], y)
        y = global_avg_pool(y)
        return self.head.apply(params['head'], y).astype(jnp.float32)

    def loss(self, params, batch):
        logits = self.apply(params, batch['images'])
        return _softmax_xent(logits, batch['labels'])


def _softmax_xent(logits, labels):
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.sum(logits * jax.nn.one_hot(labels, logits.shape[-1],
                                           dtype=logits.dtype), axis=-1)
    return jnp.mean(logz - gold)
