"""LSTM language model (the reference's lm1b example role,
examples/lm1b/language_model.py) on the functional module system.

TPU-first: the time dimension is a ``lax.scan`` (single compiled cell,
no Python unrolling), gates are one fused [x,h] @ W matmul on the MXU,
and the embedding/softmax follow the same sharding rules as the
transformer (vocab over ``model`` when tensor parallelism is on).
"""
import jax
import jax.numpy as jnp

from autodist_tpu.models.core import (Dense, Embedding, Module, ParamDef,
                                      constrain)


class LSTMCell(Module):
    """Fused-gate LSTM cell: [x, h] @ W -> (i, f, g, o)."""

    def __init__(self, in_dim, hidden, dtype=jnp.float32):
        self.in_dim, self.hidden, self.dtype = in_dim, hidden, dtype

    def param_defs(self):
        return {
            'kernel': ParamDef((self.in_dim + self.hidden,
                                4 * self.hidden),
                               ('embed', 'mlp'), 'fan_in'),
            'bias': ParamDef((4 * self.hidden,), ('mlp',), 'zeros'),
        }

    def apply(self, params, carry, x):
        h, c = carry
        z = jnp.concatenate([x, h], axis=-1).astype(self.dtype)
        gates = z @ params['kernel'].astype(self.dtype) + \
            params['bias'].astype(self.dtype)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f + 1.0) * c + \
            jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h

    def init_carry(self, batch):
        z = jnp.zeros((batch, self.hidden), self.dtype)
        return (z, z)


class LSTMLM(Module):
    """Embedding -> n_layers LSTM (scan over time) -> logits."""

    def __init__(self, vocab=10000, dim=512, hidden=1024, n_layers=2,
                 tied=False, dtype=jnp.float32):
        self.vocab, self.dim, self.hidden = vocab, dim, hidden
        self.n_layers = n_layers
        self.dtype = dtype
        self.embed = Embedding(vocab, dim, dtype=dtype)
        self.cells = [LSTMCell(dim if i == 0 else hidden, hidden,
                               dtype=dtype) for i in range(n_layers)]
        self.proj = Dense(hidden, dim, 'mlp', 'embed', dtype=dtype)
        self.tied = tied
        if not tied:
            self.head = Dense(dim, vocab, 'embed', 'vocab',
                              use_bias=False, dtype=dtype)

    def param_defs(self):
        d = {'embed': self.embed, 'proj': self.proj}
        for i, c in enumerate(self.cells):
            d['lstm_%d' % i] = c
        if not self.tied:
            d['head'] = self.head
        return d

    def apply(self, params, tokens):
        b, s = tokens.shape
        x = self.embed.apply(params['embed'], tokens)   # [b, s, d]
        x = constrain(x, ('batch', 'seq', 'embed'))
        y = jnp.transpose(x, (1, 0, 2))                 # time-major scan
        for i, cell in enumerate(self.cells):
            p = params['lstm_%d' % i]

            def step(carry, xt, cell=cell, p=p):
                return cell.apply(p, carry, xt)

            _, y = jax.lax.scan(step, cell.init_carry(b), y)
        y = jnp.transpose(y, (1, 0, 2))                 # [b, s, hidden]
        y = self.proj.apply(params['proj'], y)
        if self.tied:
            logits = self.embed.attend(params['embed'], y)
        else:
            logits = self.head.apply(params['head'], y)
        return logits.astype(jnp.float32)

    def per_token_loss_with_aux(self, params, batch):
        logits = self.apply(params, batch['tokens'])
        targets = batch['targets']
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.sum(logits * jax.nn.one_hot(
            targets, logits.shape[-1], dtype=logits.dtype), axis=-1)
        return logz - gold, jnp.zeros((), jnp.float32)

    def per_token_loss(self, params, batch):
        return self.per_token_loss_with_aux(params, batch)[0]

    def loss(self, params, batch):
        nll, _ = self.per_token_loss_with_aux(params, batch)
        mask = batch.get('mask')
        if mask is not None:
            return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
        return jnp.mean(nll)
