"""Neural Collaborative Filtering (reference examples/benchmark/ncf.py
role): GMF + MLP towers over user/item embeddings, binary logloss.

The embedding tables are the reference's canonical sparse-variable case
(PSLoadBalancing + partitioned embeddings); their ``vocab`` logical axis
marks them sparse for the Parallax/PartitionedPS builders via the pytree
adapter, and shards them over ``model`` under tensor parallelism.
"""
import jax
import jax.numpy as jnp

from autodist_tpu.models.core import Dense, Embedding, Module


class NCF(Module):
    def __init__(self, num_users, num_items, mf_dim=64,
                 mlp_dims=(256, 128, 64), dtype=jnp.float32):
        self.num_users, self.num_items = num_users, num_items
        self.mf_dim = mf_dim
        self.dtype = dtype
        self.mf_user = Embedding(num_users, mf_dim, dtype=dtype)
        self.mf_item = Embedding(num_items, mf_dim, dtype=dtype)
        mlp_in = mlp_dims[0]
        self.mlp_user = Embedding(num_users, mlp_in // 2, dtype=dtype)
        self.mlp_item = Embedding(num_items, mlp_in // 2, dtype=dtype)
        self.mlp = []
        for i in range(1, len(mlp_dims)):
            self.mlp.append(Dense(mlp_dims[i - 1], mlp_dims[i],
                                  'embed', 'mlp', dtype=dtype))
        self.head = Dense(mf_dim + mlp_dims[-1], 1, 'embed', None,
                          dtype=dtype)

    def param_defs(self):
        d = {'mf_user': self.mf_user, 'mf_item': self.mf_item,
             'mlp_user': self.mlp_user, 'mlp_item': self.mlp_item,
             'head': self.head}
        for i, m in enumerate(self.mlp):
            d['mlp_%d' % i] = m
        return d

    def apply(self, params, users, items):
        gmf = self.mf_user.apply(params['mf_user'], users) * \
            self.mf_item.apply(params['mf_item'], items)
        y = jnp.concatenate(
            [self.mlp_user.apply(params['mlp_user'], users),
             self.mlp_item.apply(params['mlp_item'], items)], axis=-1)
        for i, m in enumerate(self.mlp):
            y = jax.nn.relu(m.apply(params['mlp_%d' % i], y))
        both = jnp.concatenate([gmf, y], axis=-1)
        return self.head.apply(params['head'], both)[..., 0] \
            .astype(jnp.float32)

    def loss(self, params, batch):
        logits = self.apply(params, batch['users'], batch['items'])
        labels = batch['labels'].astype(jnp.float32)
        # stable sigmoid BCE
        return jnp.mean(jnp.maximum(logits, 0) - logits * labels +
                        jnp.log1p(jnp.exp(-jnp.abs(logits))))
