"""Model zoo: the reference's benchmark families, TPU-native.

- transformer: TransformerLM (BERT-large/GPT configs, MoE option)
- vision: ResNet50/101/152, VGG16, DenseNet121, InceptionV3
- rnn: LSTMLM (lm1b role)
- ncf: NCF recommender (sparse embeddings role)
"""
from autodist_tpu.models.core import (Dense, Embedding, LayerNorm,  # noqa: F401
                                      Mlp, Module, ParamDef, Sequential)
from autodist_tpu.models.transformer import (TransformerConfig,  # noqa: F401
                                             TransformerLM)
from autodist_tpu.models.rnn import LSTMLM  # noqa: F401
from autodist_tpu.models.ncf import NCF  # noqa: F401
from autodist_tpu.models.vision import (DenseNet, InceptionV3, ResNet,  # noqa: F401
                                        VGG)
