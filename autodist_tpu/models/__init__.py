"""models subpackage."""
