"""Multi-head attention with tensor- and sequence-parallel execution.

Heads shard over the ``model`` mesh axis (Megatron column/row split via
the logical ``heads`` axis); the sequence dimension shards over ``seq``
when the step runs in explicit (shard_map) mode, in which case the module
switches to ring attention (parallel/ring_attention.py). The reference
has neither TP nor SP (SURVEY.md §2.3) — these are the TPU-native
extension axes of the strategy space.
"""
import jax.numpy as jnp

from autodist_tpu.const import AXIS_SEQUENCE
from autodist_tpu.kernels import flash_attention as fa
from autodist_tpu.models.core import Dense, Module, constrain
from autodist_tpu.parallel.axes import (ctx_option, manual_axis,
                                        unsharded_execution)
from autodist_tpu.parallel.ring_attention import (local_flash_attention,
                                                  ring_attention)
from autodist_tpu.parallel.ulysses import ulysses_attention


class MultiHeadAttention(Module):
    """Causal (or full) self-attention; [batch, seq, embed] in/out."""

    def __init__(self, dim, num_heads, head_dim=None, causal=True,
                 dtype=jnp.float32):
        self.dim = dim
        self.num_heads = num_heads
        self.head_dim = head_dim or dim // num_heads
        self.causal = causal
        self.dtype = dtype
        inner = self.num_heads * self.head_dim
        # qkv fused: column-parallel over heads; out: row-parallel back.
        self.wqkv = Dense(dim, 3 * inner, 'embed', 'heads',
                          use_bias=False, dtype=dtype)
        self.wo = Dense(inner, dim, 'heads', 'embed',
                        use_bias=False, dtype=dtype)

    def param_defs(self):
        return {'qkv': self.wqkv, 'out': self.wo}

    def apply(self, params, x):
        b, s, _ = x.shape
        h, d = self.num_heads, self.head_dim
        qkv = self.wqkv.apply(params['qkv'], x)          # [b, s, 3hd]
        qkv = qkv.reshape(b, s, 3, h, d)
        q = jnp.transpose(qkv[:, :, 0], (0, 2, 1, 3))     # [b, h, s, d]
        k = jnp.transpose(qkv[:, :, 1], (0, 2, 1, 3))
        v = jnp.transpose(qkv[:, :, 2], (0, 2, 1, 3))

        seq_axis = manual_axis(AXIS_SEQUENCE)
        if seq_axis is not None:
            if ctx_option('sp_mode', 'ring') == 'ulysses':
                o = ulysses_attention(q, k, v, seq_axis,
                                      causal=self.causal)
            else:
                o = ring_attention(q, k, v, seq_axis, causal=self.causal)
        elif unsharded_execution() and fa.preferred(q.shape):
            # device-local long-seq data: the Pallas flash kernel (never
            # materializes the [s, s] score matrix in HBM)
            o = fa.flash_attention(q, k, v, causal=self.causal)
        else:
            o = local_flash_attention(q, k, v, causal=self.causal)
            o = constrain(o, ('batch', 'heads', 'seq', 'kv'))
        o = jnp.transpose(o, (0, 2, 1, 3)).reshape(b, s, h * d)
        return self.wo.apply(params['out'], o)
