"""Multi-head attention with tensor- and sequence-parallel execution.

Heads shard over the ``model`` mesh axis (Megatron column/row split via
the logical ``heads`` axis); the sequence dimension shards over ``seq``
when the step runs in explicit (shard_map) mode, in which case the module
switches to ring attention (parallel/ring_attention.py). The reference
has neither TP nor SP (SURVEY.md §2.3) — these are the TPU-native
extension axes of the strategy space.
"""
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from autodist_tpu.const import AXIS_DATA, AXIS_SEQUENCE
from autodist_tpu.kernels import flash_attention as fa
from autodist_tpu.models.core import Dense, Module, constrain
from autodist_tpu.parallel.axes import (active_manual_axes, ctx_option,
                                        current_mesh, live_mesh_axis,
                                        manual_axis, unsharded_execution)
from autodist_tpu.parallel.ring_attention import (local_flash_attention,
                                                  ring_attention)
from autodist_tpu.parallel.ulysses import ulysses_attention


class MultiHeadAttention(Module):
    """Causal (or full) self-attention; [batch, seq, embed] in/out."""

    def __init__(self, dim, num_heads, head_dim=None, causal=True,
                 dtype=jnp.float32):
        self.dim = dim
        self.num_heads = num_heads
        self.head_dim = head_dim or dim // num_heads
        self.causal = causal
        self.dtype = dtype
        inner = self.num_heads * self.head_dim
        # qkv fused: column-parallel over heads; out: row-parallel back.
        self.wqkv = Dense(dim, 3 * inner, 'embed', 'heads',
                          use_bias=False, dtype=dtype)
        self.wo = Dense(inner, dim, 'heads', 'embed',
                        use_bias=False, dtype=dtype)

    def param_defs(self):
        return {'qkv': self.wqkv, 'out': self.wo}

    def apply(self, params, x):
        b, s, _ = x.shape
        h, d = self.num_heads, self.head_dim
        qkv = self.wqkv.apply(params['qkv'], x)          # [b, s, 3hd]
        qkv = qkv.reshape(b, s, 3, h, d)
        q = jnp.transpose(qkv[:, :, 0], (0, 2, 1, 3))     # [b, h, s, d]
        k = jnp.transpose(qkv[:, :, 1], (0, 2, 1, 3))
        v = jnp.transpose(qkv[:, :, 2], (0, 2, 1, 3))

        seq_axis = manual_axis(AXIS_SEQUENCE)
        if seq_axis is not None:
            if ctx_option('sp_mode', 'ring') == 'ulysses':
                o = ulysses_attention(q, k, v, seq_axis,
                                      causal=self.causal)
            else:
                o = ring_attention(q, k, v, seq_axis, causal=self.causal)
        elif unsharded_execution() and fa.preferred(q.shape):
            # device-local long-seq data: the Pallas flash kernel (never
            # materializes the [s, s] score matrix in HBM)
            o = fa.flash_attention(q, k, v, causal=self.causal)
        elif self._tp_manual_shape(q.shape) is not None:
            # dp/tp GSPMD mesh at long seq: attention is independent per
            # (batch, head), so hop into a nested manual region over the
            # data+model axes and run the flash kernel on local shards —
            # GSPMD alone cannot partition an opaque pallas_call.
            o = self._tp_manual_flash(q, k, v)
        else:
            o = local_flash_attention(q, k, v, causal=self.causal)
            o = constrain(o, ('batch', 'heads', 'seq', 'kv'))
        o = jnp.transpose(o, (0, 2, 1, 3)).reshape(b, s, h * d)
        return self.wo.apply(params['out'], o)

    # -- nested-manual flash under dp/tp GSPMD -----------------------------
    def _tp_manual_shape(self, shape):
        """Per-shard [b, h, s, d] when the nested-manual flash path
        applies, else None. Conditions: no manual region already active
        (ring/Ulysses and the pipeline own their shard_maps), a mesh
        with a live data and/or heads axis, batch/head dims divisible,
        and the per-shard shape past the kernel crossover. Mesh axes
        OTHER than data/heads (pipe, seq, expert) may be live: attention
        inputs are not sharded over them, so the nested region simply
        leaves them untouched (round-2 fix — they used to drop long-seq
        attention to the jnp path silently)."""
        if active_manual_axes():
            return None
        mesh = current_mesh()
        if mesh is None:
            return None
        heads_axis = live_mesh_axis('heads')
        dp = mesh.shape.get(AXIS_DATA, 1)
        tp = mesh.shape[heads_axis] if heads_axis else 1
        if dp * tp <= 1 or shape[0] % dp or shape[1] % tp:
            return None
        local = (shape[0] // dp, shape[1] // tp, shape[2], shape[3])
        return local if fa.preferred(local) else None

    def _tp_manual_flash(self, q, k, v):
        mesh = current_mesh()
        heads_axis = live_mesh_axis('heads')
        spec = P(AXIS_DATA if mesh.shape.get(AXIS_DATA, 1) > 1 else None,
                 heads_axis)
        names = {a for a in (AXIS_DATA, heads_axis)
                 if a and mesh.shape.get(a, 1) > 1}
        from autodist_tpu.parallel.axes import shard_map_compat
        fn = shard_map_compat(
            lambda q, k, v: fa.flash_attention(q, k, v,
                                               causal=self.causal),
            mesh, (spec,) * 3, spec, axis_names=names)
        return fn(q, k, v)
