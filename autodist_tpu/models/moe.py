"""Mixture-of-experts MLP with expert parallelism.

The reference's closest feature is sparse-variable partitioning
("EP-lite", SURVEY.md §2.3); real expert parallelism is a TPU-native
extension axis. Design is the Switch/GShard dense-dispatch formulation:
top-k routing builds a dispatch tensor contracted with einsums, so expert
compute stays static-shaped (MXU/XLA-friendly, no ragged scatter) and
sharding the expert dim over the ``expert`` mesh axis makes GSPMD insert
the all-to-alls. Overflowed tokens beyond per-expert capacity are dropped
(standard Switch behavior); an auxiliary load-balancing loss is returned
via a side channel.
"""
import jax
import jax.numpy as jnp

from autodist_tpu.models.core import Dense, Module, ParamDef, constrain


class MoeMlp(Module):
    """Top-k routed expert MLP. Input/output: [batch, seq, dim]."""

    def __init__(self, dim, hidden, n_experts, top_k=2,
                 capacity_factor=2.0, dtype=jnp.float32,
                 act=jax.nn.gelu):
        self.dim, self.hidden = dim, hidden
        self.n_experts = n_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.dtype = dtype
        self.act = act
        self.router = Dense(dim, n_experts, 'embed', None,
                            use_bias=False, dtype=jnp.float32)

    def param_defs(self):
        return {
            'router': self.router,
            'up': ParamDef((self.n_experts, self.dim, self.hidden),
                           ('expert', 'embed', 'mlp'), 'fan_in'),
            'down': ParamDef((self.n_experts, self.hidden, self.dim),
                             ('expert', 'mlp', 'embed'), 'fan_in'),
        }

    def apply(self, params, x):
        b, s, d = x.shape
        e = self.n_experts
        cap = max(1, int(self.capacity_factor * s * self.top_k / e))

        logits = self.router.apply(params['router'],
                                   x.astype(jnp.float32))   # [b,s,e]
        probs = jax.nn.softmax(logits, axis=-1)

        # top-k expert choice per token
        gate_vals, gate_idx = jax.lax.top_k(probs, self.top_k)  # [b,s,k]
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, -1, keepdims=True), 1e-9)

        # position of each (token, choice) in its expert's buffer via
        # cumulative count over the flattened (s*k) routing sequence
        choice_oh = jax.nn.one_hot(gate_idx, e, dtype=jnp.int32)  # [b,s,k,e]
        flat = choice_oh.reshape(b, s * self.top_k, e)
        pos = jnp.cumsum(flat, axis=1) - flat                 # [b,sk,e]
        pos = jnp.sum(pos * flat, axis=-1).reshape(b, s, self.top_k)
        in_cap = pos < cap

        # dispatch/combine tensors [b, s, k, e, cap] -> summed over k
        pos_oh = jax.nn.one_hot(pos, cap, dtype=self.dtype)   # [b,s,k,cap]
        disp = (choice_oh.astype(self.dtype)[..., None] *
                pos_oh[..., None, :] *
                in_cap[..., None, None].astype(self.dtype))   # [b,s,k,e,cap]
        combine = disp * gate_vals[..., None, None].astype(self.dtype)
        disp = jnp.sum(disp, axis=2)                          # [b,s,e,cap]
        combine = jnp.sum(combine, axis=2)                    # [b,s,e,cap]

        xe = jnp.einsum('bsec,bsd->becd', disp, x.astype(self.dtype))
        xe = constrain(xe, ('batch', 'expert', None, 'embed'))
        h = self.act(jnp.einsum('becd,edh->bech', xe,
                                params['up'].astype(self.dtype)))
        h = constrain(h, ('batch', 'expert', None, 'mlp'))
        ye = jnp.einsum('bech,ehd->becd', h,
                        params['down'].astype(self.dtype))
        y = jnp.einsum('bsec,becd->bsd', combine, ye)

        # load-balance aux loss (Switch eq. 4): e * sum_e f_e * P_e
        f = jnp.mean(jnp.sum(choice_oh[:, :, 0], axis=1).astype(
            jnp.float32) / s, axis=0)                         # [e]
        p = jnp.mean(probs, axis=(0, 1))
        self_aux = e * jnp.sum(f * p)
        return y, self_aux
