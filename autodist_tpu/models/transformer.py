"""Transformer language model — the framework's flagship model family.

Covers the reference's BERT-large benchmark role (BASELINE.md: BERT-large
tokens/s) and the lm1b LSTM example's role as the language-model case,
built TPU-first: bfloat16 matmuls on the MXU, logical-axis sharding for
DP/TP/SP/EP, ring attention for long context, remat-friendly block
structure (scan-over-layers so XLA compiles one block).
"""
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from autodist_tpu.const import AXIS_PIPELINE, AXIS_SEQUENCE
from autodist_tpu.models.attention import MultiHeadAttention
from autodist_tpu.models.core import (Dense, Embedding, LayerNorm, Mlp,
                                      Module, ParamDef, constrain)
from autodist_tpu.parallel.axes import ctx_option, manual_axis


@dataclass
class TransformerConfig:
    vocab: int = 32000
    dim: int = 1024
    n_layers: int = 24
    n_heads: int = 16
    mlp_ratio: int = 4
    max_len: int = 2048
    causal: bool = True
    tied_embeddings: bool = True
    dtype: object = jnp.bfloat16
    # remat: False = none; True = checkpoint each block (recompute the
    # whole block in backward); 'save_attn' = checkpoint each block but
    # SAVE the post-attention residual, so backward recomputes only the
    # LN2+MLP half at one extra [b,s,d] save per layer. On v5e BERT
    # bench shapes the two are perf-equal (step time is dominated
    # elsewhere); 'save_attn' matters when attention is the expensive
    # recompute (long sequences without the flash kernel). Also
    # 'dots' (save every matmul output — recompute only elementwise
    # work; highest-memory selective tier, exceeds a 16 GB chip for
    # bert_large from batch 128) and 'dots_no_batch' (save only
    # batch-free dots — effectively full remat here). See _block_fn.
    remat: object = False
    scan_layers: bool = True     # stack blocks + lax.scan (1 compile/block)
    # Chunked cross-entropy: target rows (batch*seq positions) per chunk
    # of the lm-head + softmax computation. 0 = off (materialize full
    # [b, s, vocab] fp32 logits). On, the loss scans over sequence
    # chunks with jax.checkpoint, so peak memory holds one
    # [b, s/n, vocab] slab instead of the whole thing. A memory
    # feature, not a speed feature: at BERT-large bench shapes it frees
    # ~8 GB (batch 768 compiles where 640 OOMed before) at unchanged
    # tokens/s; it is what makes big-vocab / long-seq losses fit.
    loss_chunk: int = 0
    moe_experts: int = 0         # >0: MoE MLP with this many experts
    moe_top_k: int = 2
    moe_aux_coef: float = 0.01   # load-balance loss weight

    @classmethod
    def bert_large(cls, **kw):
        """BERT-large class config (24L/1024d/16h) — reference headline
        pre-training model (docs/usage/performance.md:7)."""
        d = dict(vocab=30522, dim=1024, n_layers=24, n_heads=16,
                 causal=False, max_len=512)
        d.update(kw)
        return cls(**d)

    @classmethod
    def gpt_small(cls, **kw):
        d = dict(vocab=32000, dim=768, n_layers=12, n_heads=12,
                 causal=True, max_len=1024)
        d.update(kw)
        return cls(**d)

    @classmethod
    def tiny(cls, **kw):
        d = dict(vocab=256, dim=64, n_layers=2, n_heads=4, max_len=128)
        d.update(kw)
        return cls(**d)


class Block(Module):
    """Pre-LN transformer block; MoE MLP when cfg.moe_experts > 0.

    ``apply`` returns ``(x, aux)`` where aux is the router load-balance
    loss contribution (0.0 for dense blocks)."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.ln1 = LayerNorm(cfg.dim, dtype=cfg.dtype)
        self.attn = MultiHeadAttention(cfg.dim, cfg.n_heads,
                                       causal=cfg.causal, dtype=cfg.dtype)
        self.ln2 = LayerNorm(cfg.dim, dtype=cfg.dtype)
        if cfg.moe_experts:
            from autodist_tpu.models.moe import MoeMlp
            self.mlp = MoeMlp(cfg.dim, cfg.dim * cfg.mlp_ratio,
                              cfg.moe_experts, top_k=cfg.moe_top_k,
                              dtype=cfg.dtype)
        else:
            self.mlp = Mlp(cfg.dim, cfg.dim * cfg.mlp_ratio,
                           dtype=cfg.dtype)

    def param_defs(self):
        return {'ln1': self.ln1, 'attn': self.attn,
                'ln2': self.ln2, 'mlp': self.mlp}

    def apply(self, params, x):
        x = x + self.attn.apply(params['attn'],
                                self.ln1.apply(params['ln1'], x))
        # named so remat='save_attn' can keep it while recomputing the rest
        x = checkpoint_name(x, 'attn_out')
        h = self.mlp.apply(params['mlp'],
                           self.ln2.apply(params['ln2'], x))
        aux = jnp.zeros((), jnp.float32)
        if self.cfg.moe_experts:
            h, aux = h
        x = x + h
        return constrain(x, ('batch', 'seq', 'embed')), aux


class TransformerLM(Module):
    """Embedding -> N blocks -> final LN -> logits.

    With ``scan_layers`` the block params are stacked along a leading
    ``stage`` logical axis and the forward is a ``lax.scan`` — one
    compiled block regardless of depth, and the natural substrate for
    pipeline parallelism (the ``stage`` axis shards over ``pipe``).
    """

    def __init__(self, cfg):
        self.cfg = cfg
        self.embed = Embedding(cfg.vocab, cfg.dim, dtype=cfg.dtype)
        # 'pos' is deliberately unmapped (replicated): in sequence-parallel
        # mode every shard looks up its own global positions locally.
        self.pos_embed = Embedding(cfg.max_len, cfg.dim,
                                   vocab_axis='pos', dtype=cfg.dtype)
        self.block = Block(cfg)
        self.ln_f = LayerNorm(cfg.dim, dtype=cfg.dtype)
        if not cfg.tied_embeddings:
            self.lm_head = Dense(cfg.dim, cfg.vocab, 'embed', 'vocab',
                                 use_bias=False, dtype=cfg.dtype)

    def param_defs(self):
        d = {'embed': self.embed, 'pos_embed': self.pos_embed,
             'ln_f': self.ln_f}
        if not self.cfg.tied_embeddings:
            d['lm_head'] = self.lm_head
        if self.cfg.scan_layers:
            d['blocks'] = _Stacked(self.block, self.cfg.n_layers)
        else:
            for i in range(self.cfg.n_layers):
                d['block_%03d' % i] = self.block
        return d

    def apply(self, params, tokens):
        return self.apply_with_aux(params, tokens)[0]

    def apply_with_aux(self, params, tokens):
        """Returns (logits, aux) where aux is the summed MoE router
        load-balance loss (0.0 for dense configs)."""
        x, aux_total = self.hidden_with_aux(params, tokens)
        logits = self._head_logits(params, x)
        return constrain(logits.astype(jnp.float32),
                         ('batch', 'seq', 'vocab')), aux_total

    def _head_logits(self, params, x):
        """LM-head logits (model dtype) for hidden states of any
        leading shape (..., dim)."""
        if self.cfg.tied_embeddings:
            return self.embed.attend(params['embed'], x)
        return self.lm_head.apply(params['lm_head'], x)

    def _embedded(self, params, tokens):
        """Embedding + positions (the pipeline prologue)."""
        _, s = tokens.shape
        x = self.embed.apply(params['embed'], tokens)
        # global positions: offset by the manual seq-shard index when the
        # sequence axis runs inside shard_map (ring attention mode)
        seq_axis = manual_axis(AXIS_SEQUENCE)
        pos = jnp.arange(s)
        if seq_axis is not None:
            pos = pos + jax.lax.axis_index(seq_axis) * s
        x = x + self.pos_embed.apply(params['pos_embed'], pos)[None]
        return constrain(x, ('batch', 'seq', 'embed'))

    def _block_fn(self):
        """Single-block apply with the remat policy applied.

        ``cfg.remat``: False (no remat), True (full — recompute the
        whole block in the backward), or a named selective policy:
        'save_attn' (keep attention outputs), 'dots' (keep every
        matmul output — recompute only elementwise/norm work; the
        highest-memory selective tier), 'dots_no_batch' (keep only
        batch-free dot outputs — in a transformer block effectively
        full remat, kept for completeness).
        """
        cfg = self.cfg
        block_fn = self.block.apply
        if isinstance(cfg.remat, str):
            policies = {
                'save_attn':
                    jax.checkpoint_policies.save_only_these_names(
                        'attn_out'),
                'dots': jax.checkpoint_policies.checkpoint_dots,
                'dots_no_batch':
                    jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable,
            }
            if cfg.remat not in policies:
                raise ValueError(
                    'unknown remat mode %r (expected False, True, or '
                    'one of %s)' % (cfg.remat, sorted(policies)))
            return jax.checkpoint(block_fn, policy=policies[cfg.remat])
        if cfg.remat:
            return jax.checkpoint(block_fn)
        return block_fn

    def hidden_with_aux(self, params, tokens):
        """Final hidden states (post ln_f) and the MoE aux loss —
        everything except the lm-head, so losses can chunk the head."""
        cfg = self.cfg
        x = self._embedded(params, tokens)
        block_fn = self._block_fn()
        aux_total = jnp.zeros((), jnp.float32)
        pipe_axis = manual_axis(AXIS_PIPELINE)
        if pipe_axis is not None:
            if not cfg.scan_layers:
                raise ValueError(
                    'pipeline parallelism requires scan_layers=True '
                    '(blocks must be stage-stacked to shard over pipe)')
            from autodist_tpu.parallel.pipeline import gpipe, one_f_one_b
            pipe_fn = one_f_one_b \
                if ctx_option('pp_schedule', 'gpipe') == '1f1b' else gpipe
            x, aux_pipe = pipe_fn(block_fn, params['blocks'], x, pipe_axis,
                                  ctx_option('microbatches', 1))
            aux_total = aux_total + aux_pipe
        elif cfg.scan_layers:
            def body(carry, layer_params):
                h, aux = carry
                h, a = block_fn(layer_params, h)
                return (h, aux + a), None
            (x, aux_total), _ = jax.lax.scan(
                body, (x, aux_total), params['blocks'])
        else:
            for i in range(cfg.n_layers):
                x, a = block_fn(params['block_%03d' % i], x)
                aux_total = aux_total + a
        x = self.ln_f.apply(params['ln_f'], x)
        return x, aux_total

    def per_token_loss(self, params, batch):
        return self.per_token_loss_with_aux(params, batch)[0]

    @property
    def aux_loss_weight(self):
        return self.cfg.moe_aux_coef if self.cfg.moe_experts else 0.0

    def per_token_loss_with_aux(self, params, batch):
        """([batch, seq] token NLL, aux loss); expects {'tokens',
        'targets'}.

        Shape-preserving on purpose: in sequence-parallel mode this runs
        inside shard_map over local seq shards and the trainer reduces.
        Under SP, MoE routing groups are the local seq shards (GShard
        grouping), so capacity/dropping is per-shard."""
        targets = batch['targets']
        pipe_axis = manual_axis(AXIS_PIPELINE)
        if pipe_axis is not None and \
                ctx_option('pp_schedule', 'gpipe') == '1f1b' and \
                ctx_option('pp_variant', 'auto') != 'legacy':
            return self._loss_1f1b(params, batch, pipe_axis)
        x, aux = self.hidden_with_aux(params, batch['tokens'])
        b, s = targets.shape
        n = self._ce_chunks(s, b * s)
        if n > 1:
            # Chunked CE: scan over sequence chunks; jax.checkpoint means
            # backward recomputes each chunk's logits instead of saving
            # an [b, s, vocab] residual. Chunking the SEQ dim (not
            # flattened rows) keeps the batch dim intact, so DP sharding
            # propagates through the reshape without communication.
            d = x.shape[-1]
            xs = x.reshape(b, n, s // n, d).swapaxes(0, 1)
            ts = targets.reshape(b, n, s // n).swapaxes(0, 1)
            ckpt = jax.checkpoint(self._chunk_nll)
            _, nll = jax.lax.scan(
                lambda c, inp: (c, ckpt(params, *inp)), None, (xs, ts))
            nll = nll.swapaxes(0, 1).reshape(b, s)
        else:
            nll = self._chunk_nll(params, x, targets)
        return nll, aux

    def _loss_1f1b(self, params, batch, pipe_axis):
        """Pipelined loss via the FUSED 1F1B schedule: the embedding
        folds into the first stage (``head_fn``) and the lm-head + NLL
        into the last (``tail_fn``), so the pipeline's interface is
        token-sized — no full-batch ``[B, s, dim]`` activation stack,
        ``[B, s, vocab]`` logits slab, or input cotangent ever
        materializes, and the custom-vjp backward bounds each rank's
        live activations at ``2(pp-1)+1`` microbatches (true 1F1B
        working set, independent of the microbatch count).
        ``loss_chunk`` is subsumed — each microbatch IS a head chunk."""
        cfg = self.cfg
        if not cfg.scan_layers:
            raise ValueError(
                'pipeline parallelism requires scan_layers=True '
                '(blocks must be stage-stacked to shard over pipe)')
        from autodist_tpu.parallel.pipeline import one_f_one_b

        def head(p, tok_mb):
            return self._embedded(p, tok_mb)

        def tail(p, h, tgt):
            h = self.ln_f.apply(p['ln_f'], h)
            return self._chunk_nll(p, h, tgt)

        # Pass ONLY the subtrees head/tail actually touch: the fused
        # backward carries + psums a zeros-like of these trees, so
        # handing it the full params dict would add two block-stack-
        # sized gradient buffers for nothing.
        head_params = {k: params[k] for k in ('embed', 'pos_embed')}
        tail_params = {
            k: params[k]
            for k in ('ln_f',
                      'embed' if cfg.tied_embeddings else 'lm_head')}
        return one_f_one_b(self._block_fn(), params['blocks'],
                           batch['tokens'], pipe_axis,
                           ctx_option('microbatches', 1),
                           tail_fn=tail, extra=batch['targets'],
                           tail_params=tail_params,
                           head_fn=head, head_params=head_params,
                           variant=ctx_option('pp_variant', 'auto'))

    def _chunk_nll(self, params, x, targets):
        logits = constrain(self._head_logits(params, x).astype(jnp.float32),
                           ('batch', 'seq', 'vocab'))
        logz = jax.nn.logsumexp(logits, axis=-1)
        # one-hot contraction, not take_along_axis: partitions cleanly
        # when the vocab dim is tensor-sharded
        gold = jnp.sum(logits * jax.nn.one_hot(targets, logits.shape[-1],
                                               dtype=logits.dtype), axis=-1)
        return logz - gold

    def _ce_chunks(self, s, rows):
        """Number of sequence chunks for chunked CE: the largest chunk
        count that divides ``s`` while keeping >= loss_chunk rows per
        chunk (0 or rows <= loss_chunk -> 1 = unchunked)."""
        chunk = self.cfg.loss_chunk
        if not chunk or rows <= chunk:
            return 1
        n = max(1, min(s, rows // chunk))
        while s % n:
            n -= 1
        return n

    def loss(self, params, batch):
        """Mean token cross-entropy (+ MoE balance loss), optional mask."""
        nll, aux = self.per_token_loss_with_aux(params, batch)
        mask = batch.get('mask')
        if mask is not None:
            ce = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
        else:
            ce = jnp.mean(nll)
        return ce + self.cfg.moe_aux_coef * aux


class _Stacked(Module):
    """A module's params stacked n times along a leading 'stage' axis."""

    def __init__(self, inner, n):
        self.inner = inner
        self.n = n

    def init(self, rng):
        keys = jax.random.split(rng, self.n)
        return jax.vmap(self.inner.init)(keys)

    def axes(self):
        inner_axes = self.inner.axes()
        return jax.tree.map(
            lambda a: ('stage',) + tuple(a),
            inner_axes,
            is_leaf=lambda x: isinstance(x, tuple) and
            all(isinstance(v, (str, type(None))) for v in x))

    def param_defs(self):  # pragma: no cover - init/axes overridden
        return {'inner': self.inner}
