"""Python face of the native data loader (native/dataloader.cc).

Fixed-size binary records (ADTR1 format) -> numpy batches, prefetched by
a native reader thread so host IO overlaps device steps. Per-host data
sharding (``shard_id``/``num_shards``) implements the multi-host side of
the reference's feed-splitting contract (remapper.py:109-123): within a
host the Session/Trainer splits the batch over local replicas; across
hosts each process loads only its shard.

A pure-python fallback keeps the API alive where g++ is unavailable.
"""
import ctypes
import os
import struct

import numpy as np

from autodist_tpu.utils import logging

MAGIC = b'ADTR1\x00\x00\x00'
_LIB = None


def _lib():
    global _LIB
    if _LIB is None:
        from autodist_tpu.native_build import build
        path = build('dataloader.cc', shared=True)
        lib = ctypes.CDLL(path)
        lib.adl_create.restype = ctypes.c_void_p
        lib.adl_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
            ctypes.c_int64, ctypes.c_int, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64]
        lib.adl_next.restype = ctypes.c_int64
        lib.adl_next.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.adl_epoch.restype = ctypes.c_int64
        lib.adl_epoch.argtypes = [ctypes.c_void_p]
        lib.adl_destroy.argtypes = [ctypes.c_void_p]
        _LIB = lib
    return _LIB


def write_records(path, array):
    """Write a [num_records, ...] array as an ADTR1 record file."""
    array = np.ascontiguousarray(array)
    record_size = array.nbytes // array.shape[0]
    with open(path, 'wb') as f:
        f.write(MAGIC)
        f.write(struct.pack('<qq', record_size, array.shape[0]))
        f.write(array.tobytes())
    return path


def read_record_header(path):
    with open(path, 'rb') as f:
        magic = f.read(8)
        if magic != MAGIC:
            raise ValueError('%s is not an ADTR1 record file' % path)
        record_size, num_records = struct.unpack('<qq', f.read(16))
    return record_size, num_records


class DataLoader:
    """Iterate batches of records as numpy arrays.

    Args:
        files: record files (all with the same record layout).
        batch_records: records per emitted batch.
        record_shape / record_dtype: logical layout of one record.
        shuffle/seed: deterministic shuffling per epoch.
        shard_id/num_shards: host-sharded loading.
        native: force (True) / forbid (False) the C++ path; default auto.
    """

    def __init__(self, files, batch_records, record_shape, record_dtype,
                 shuffle=True, seed=0, shard_id=0, num_shards=1,
                 queue_cap=4, native=None):
        self.files = [os.fspath(f) for f in files]
        self.batch_records = int(batch_records)
        self.record_shape = tuple(record_shape)
        self.record_dtype = np.dtype(record_dtype)
        self.record_size = int(np.prod(self.record_shape) *
                               self.record_dtype.itemsize)
        for f in self.files:
            rec, _ = read_record_header(f)
            if rec != self.record_size:
                raise ValueError('record size mismatch in %s: %d != %d'
                                 % (f, rec, self.record_size))
        self._handle = None
        self._native = native
        self._py_state = None
        if native is not False:
            try:
                lib = _lib()
                arr = (ctypes.c_char_p * len(self.files))(
                    *[f.encode() for f in self.files])
                self._handle = lib.adl_create(
                    arr, len(self.files), self.record_size,
                    self.batch_records, 1, seed, int(bool(shuffle)),
                    shard_id, num_shards, queue_cap)
                if not self._handle:
                    raise RuntimeError('adl_create failed (bad files?)')
            except Exception as e:  # noqa: BLE001
                if native:
                    raise
                logging.warning('Native loader unavailable (%s); '
                                'using python fallback', e)
        if self._handle is None:
            self._init_python(shuffle, seed, shard_id, num_shards)

    # -- python fallback ---------------------------------------------------
    def _init_python(self, shuffle, seed, shard_id, num_shards):
        records = []
        for f in self.files:
            _, n = read_record_header(f)
            data = np.fromfile(f, dtype=np.uint8, offset=24)
            data = data.reshape(n, self.record_size)
            records.append(data)
        all_records = np.concatenate(records, axis=0)
        mask = np.arange(all_records.shape[0]) % num_shards == shard_id
        self._py_records = all_records[mask]
        self._py_state = {'rng': np.random.RandomState(seed),
                          'order': None, 'pos': 0, 'epoch': 0,
                          'shuffle': shuffle}

    def _py_next(self):
        st = self._py_state
        n = self._py_records.shape[0]
        out = np.empty((self.batch_records, self.record_size), np.uint8)
        for b in range(self.batch_records):
            if st['order'] is None or st['pos'] == n:
                st['order'] = (st['rng'].permutation(n) if st['shuffle']
                               else np.arange(n))
                if st['pos'] == n:
                    st['epoch'] += 1
                st['pos'] = 0
            out[b] = self._py_records[st['order'][st['pos']]]
            st['pos'] += 1
        return out

    # -- API ---------------------------------------------------------------
    def next_batch(self):
        """[batch_records, *record_shape] array of record_dtype."""
        if self._handle is not None:
            buf = ctypes.create_string_buffer(
                self.batch_records * self.record_size)
            got = _lib().adl_next(self._handle, buf)
            if got < 0:
                raise RuntimeError('native loader read error')
            raw = np.frombuffer(buf, dtype=np.uint8)
        else:
            raw = self._py_next().reshape(-1)
        arr = raw.view(self.record_dtype)
        return arr.reshape((self.batch_records,) +
                           self.record_shape).copy()

    @property
    def epoch(self):
        if self._handle is not None:
            return int(_lib().adl_epoch(self._handle))
        return self._py_state['epoch']

    def __iter__(self):
        while True:
            yield self.next_batch()

    def close(self):
        if self._handle is not None:
            _lib().adl_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
