"""Input pipeline: native prefetching record loader + host sharding."""
from autodist_tpu.data.loader import (DataLoader, read_record_header,  # noqa: F401
                                      write_records)
