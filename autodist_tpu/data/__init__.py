"""Input pipeline: native prefetching record loader + host sharding."""
from autodist_tpu.data.loader import (DataLoader, read_record_header,  # noqa: F401
                                      write_records)
from autodist_tpu.data.prefetch import prefetch_to_device  # noqa: F401
