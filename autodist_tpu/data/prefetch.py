"""Device prefetch: overlap host->device transfer with device compute.

The reference overlaps input IO with compute through tf.data + the TF
runtime's prefetch ops; the native loader (native/dataloader.cc) covers
the host IO half here. This covers the device half: ``device_put`` is
asynchronous in JAX, so keeping ``size`` placed batches in flight means
the transfer of batch N+1 rides along while the step on batch N runs —
the jax idiom replacing tf.data's ``prefetch_to_device``.
"""
import collections


def prefetch_to_device(iterator, place_fn, size=2):
    """Yield device-placed batches with ``size`` batches in flight.

    Args:
        iterator: iterable of host batches.
        place_fn: host batch -> device arrays (e.g.
            ``Trainer.shard_batch`` — async; must not block).
        size: number of placed batches to keep in flight (>= 1).

    Yields:
        placed batches, in order.
    """
    if size < 1:
        raise ValueError('prefetch size must be >= 1, got %d' % size)
    buf = collections.deque()
    it = iter(iterator)
    pending = []   # a source/placement error, deferred until buf drains

    def fill():
        if pending:
            return False
        try:
            buf.append(place_fn(next(it)))
        except StopIteration:
            return False
        except Exception as e:   # noqa: BLE001 - re-raised after drain
            # don't drop the up-to-`size` good batches already placed:
            # surface the error only once they have been consumed
            pending.append(e)
            return False
        return True

    for _ in range(size):
        if not fill():
            break
    while buf:
        out = buf.popleft()
        fill()
        yield out
    if pending:
        raise pending[0]
