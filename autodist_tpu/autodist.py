"""User-facing engine: the :class:`AutoDist` object.

Reference parity (``autodist/autodist.py:297-322``): construct with a
resource-spec YAML + a strategy builder; capture the model under
``.scope()``; then either ``create_distributed_session()`` (TF1-style) or
``.function()`` (TF2-style). Chief/worker identity comes from the
``AUTODIST_WORKER`` env flag (autodist.py:40-41): the chief builds and
serializes the strategy, workers deserialize it by ``AUTODIST_STRATEGY_ID``
(autodist.py:100-109) and every process independently lowers it
(docs/design/architecture.rst:43-48).
"""
import atexit
import base64
import json
import os
import time

import numpy as np

from autodist_tpu.const import DEFAULT_COORD_PORT, ENV
from autodist_tpu.frontend import graph as fe
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.parallel.mesh import mesh_from_strategy
from autodist_tpu.parallel.plan import ExecutionPlan
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.runtime.cluster import Cluster
from autodist_tpu.runtime.session import Session
from autodist_tpu.strategy import base as strategy_base
from autodist_tpu.strategy.builders import PSLoadBalancing
from autodist_tpu.utils import logging

IS_AUTODIST_WORKER = bool(ENV.AUTODIST_WORKER.val)
IS_AUTODIST_CHIEF = not IS_AUTODIST_WORKER

_DEFAULT_AUTODIST = {}


def set_default_autodist(o):
    """Register the process's AutoDist instance (one per process)."""
    if os.getpid() in _DEFAULT_AUTODIST:
        raise NotImplementedError(
            'Currently only one AutoDist instance is allowed in one process.')
    _DEFAULT_AUTODIST[os.getpid()] = o


def get_default_autodist():
    return _DEFAULT_AUTODIST.get(os.getpid(), None)


def _default_resource_info():
    """Single-node spec from the locally visible jax devices."""
    import jax
    devs = jax.local_devices()
    accel = [d.id for d in devs if d.platform not in ('cpu',)]
    node = {'address': 'localhost', 'chief': True, 'cpus': [0],
            'network_bandwidth': 100}
    if accel:
        node['tpus'] = accel
    else:
        node['gpus'] = list(range(len(devs)))  # virtual CPU devices
    return {'nodes': [node]}


class AutoDist:
    """Distributed-training engine with minimal-code-change ergonomics.

    Args:
        resource_spec_file: path to a resource spec YAML (reference format,
            plus optional ``tpus:`` / ``mesh:`` keys). Defaults to a
            single-node spec over all local devices.
        strategy_builder: a StrategyBuilder (default PSLoadBalancing, as in
            the reference autodist.py:70).
    """

    def __init__(self, resource_spec_file=None, strategy_builder=None,
                 resource_info=None):
        set_default_autodist(self)
        if resource_spec_file is None and resource_info is None and \
                ENV.SYS_RESOURCE_PATH.val:
            # reference const.py:55-89: SYS_RESOURCE_PATH supplies the
            # resource spec when the ctor doesn't
            resource_spec_file = ENV.SYS_RESOURCE_PATH.val
        if resource_spec_file is not None:
            self._resource_spec = ResourceSpec(
                resource_file=resource_spec_file)
        else:
            self._resource_spec = ResourceSpec(
                resource_info=resource_info or _default_resource_info())
        self._strategy_builder = strategy_builder or PSLoadBalancing()
        self._original_graph_item = None
        self._transformed = None      # (strategy, mesh, plan)
        self._session = None
        self._cluster = Cluster(self._resource_spec)
        self._built = False
        self._coord = None            # coord-service client (multi-process)
        self._coord_proc = None       # service process if we started it
        # captured BEFORE this object mutates the env: a launcher
        # (launch_cli / pod runtime) marks its processes with
        # AUTODIST_PROCESS_ID; the ssh-launch chief sets it later itself.
        self._ext_launched = \
            os.environ.get(ENV.AUTODIST_PROCESS_ID.name) is not None
        # ad.function state
        self._fn_cache = {}

    # -- capture -----------------------------------------------------------
    def scope(self):
        """Context manager capturing the code block to be distributed
        (reference autodist.py:309-322)."""
        self._original_graph_item = GraphItem(graph=fe.Graph())
        return self._original_graph_item.graph

    # -- strategy ----------------------------------------------------------
    def build_strategy(self):
        """Build the Strategy for the captured graph (autodist.py:91-98)."""
        return self._strategy_builder.build(
            self._original_graph_item, self._resource_spec)

    def _build_or_load_strategy(self):
        self._original_graph_item.prepare()
        if IS_AUTODIST_CHIEF:
            s = self.build_strategy()
            s.serialize()
            if self._coord is not None:
                # publish for same-binary (pod-style) workers that have no
                # pre-set strategy id (the coordinator's scp equivalent);
                # keys carry the launcher's run nonce so a stale/reused
                # service cannot serve a previous run's strategy
                ns = ENV.AUTODIST_RUN_ID.val
                blob = base64.b64encode(str(s).encode()).decode()
                self._coord.set('strategy/%s/blob' % ns, blob)
                self._coord.set('strategy/%s/id' % ns, s.id)
        else:
            strategy_id = ENV.AUTODIST_STRATEGY_ID.val
            if strategy_id:
                s = strategy_base.Strategy.deserialize(strategy_id)
            elif self._coord is not None:
                ns = ENV.AUTODIST_RUN_ID.val
                self._coord.wait_key('strategy/%s/id' % ns,
                                     timeout_s=120.0)
                blob = self._coord.get('strategy/%s/blob' % ns)
                d = json.loads(base64.b64decode(blob).decode())
                s = strategy_base.Strategy.from_dict(d)
            else:
                raise RuntimeError(
                    'Worker process needs AUTODIST_STRATEGY_ID set (or a '
                    'coord service to fetch the strategy from)')
        return s

    def _compile_strategy(self, strategy, resolver=None, compiler=None):
        logging.debug('Raw strategy: %s', strategy)
        if compiler is None:
            compiler = strategy_base.StrategyCompiler(
                self._original_graph_item)
        if resolver is not None:
            compiler.set_device_resolver(resolver)
        compiled = compiler.compile(strategy)
        logging.info('Compiled strategy: %s', compiled)
        return compiled

    @property
    def _externally_launched(self):
        """True when a launcher (launch_cli / pod runtime) already started
        one process per host — the chief must not re-launch over ssh."""
        return self._ext_launched

    def _ensure_control_plane(self):
        """Bring up / connect to the native coord service (multi-process
        runs only). The chief starts it; every process gets a client."""
        nodes = list(self._resource_spec.nodes)
        multi = ENV.AUTODIST_NUM_PROCESSES.val > 1 or len(nodes) > 1
        if not multi or self._coord is not None:
            return
        if IS_AUTODIST_CHIEF and not self._externally_launched:
            # ssh-launch mode: claim identity before workers exist
            os.environ.setdefault(ENV.AUTODIST_NUM_PROCESSES.name,
                                  str(len(nodes)))
            os.environ.setdefault(ENV.AUTODIST_PROCESS_ID.name, '0')
        from autodist_tpu.runtime import coord_client
        from autodist_tpu.runtime.cluster import is_local_address
        addr = ENV.AUTODIST_COORD_SERVICE_ADDR.val or \
            '%s:%d' % (self._resource_spec.chief, DEFAULT_COORD_PORT)
        host, port = addr.rsplit(':', 1)
        # The chief process runs on the chief node by definition (identity
        # is env-based), so it hosts the service whenever the configured
        # host names its own node — even if that NIC IP is not locally
        # recognizable (Debian 127.0.1.1-style hostname resolution).
        chief_hosts_service = IS_AUTODIST_CHIEF and (
            host == self._resource_spec.chief or is_local_address(host))
        all_local = all(is_local_address(n) for n in nodes)
        if chief_hosts_service:
            bind = '127.0.0.1' if all_local else '0.0.0.0'
            self._coord_proc = coord_client.ensure_service(
                int(port), bind=bind)
            if self._coord_proc is not None and \
                    not self._externally_launched:
                # ssh-launch mode: the chief owns the service lifetime.
                # Externally-launched runs (launch_cli / pod): the launcher
                # (or the next run, which reuses a still-listening service)
                # owns it — the chief may finish while workers still need
                # it, so it must not tear it down here.
                atexit.register(self._coord_proc.terminate)
        # all-local runs bind the service to loopback (ADVICE r1: don't
        # expose an unauthenticated service on the NIC), so every process
        # must also CONNECT via loopback even when the spec names the
        # node by its NIC IP
        connect_host = '127.0.0.1' if all_local else host
        self._coord = coord_client.connect_with_retry(
            (connect_host, int(port)))
        # PS data-plane endpoints (loose mode): every process brings up
        # the endpoints local to ITS host (ensure_service is idempotent,
        # so co-located processes race benignly) — endpoints on non-chief
        # PS nodes are started by the worker process running there;
        # variables land on the endpoint their reduction_destination maps
        # to (session.assign_ps_endpoints) — the reference's
        # one-tf.Server-per-PS-node layout (utils/server_starter.py:48-75).
        for ep_host, ep_port in coord_client.ps_endpoints():
            if is_local_address(ep_host):
                proc = coord_client.ensure_service(
                    ep_port, bind='127.0.0.1' if all_local else '0.0.0.0')
                if proc is not None and not self._externally_launched:
                    atexit.register(proc.terminate)
        if self._externally_launched and not ENV.AUTODIST_STRATEGY_ID.val:
            # Co-started processes (launch_cli / pod) exchange the
            # strategy through coord-service keys: clear any stale keys a
            # reused service may hold BEFORE anyone waits on them; the
            # barrier guarantees no worker reads until the chief's
            # deletes have landed. ssh-launched workers carry
            # AUTODIST_STRATEGY_ID and never touch these keys — and the
            # ssh chief (which launches them only later) is not a party,
            # so they must NOT join this barrier.
            ns = ENV.AUTODIST_RUN_ID.val
            if IS_AUTODIST_CHIEF:
                self._coord.delete('strategy/%s/id' % ns)
                self._coord.delete('strategy/%s/blob' % ns)
                # a reused service may hold a PREVIOUS run's init-done
                # marker: left in place it would let this run's workers
                # skip the barrier below and read strategy keys before
                # the deletes above have landed
                self._coord.delete('ctrl/init-done/%s' % ns)
                self._coord.barrier('ctrl/init/%s' % ns,
                                    ENV.AUTODIST_NUM_PROCESSES.val,
                                    timeout_s=120.0)
                # elastic rejoin: record that the init rendezvous
                # happened, so a supervised REPLACEMENT worker started
                # after a crash doesn't block on a barrier its original
                # cohort already passed (the strategy keys are stable
                # from here on)
                self._coord.set('ctrl/init-done/%s' % ns, '1')
            elif ENV.AUTODIST_ELASTIC_JOIN.val:
                # a live JOINer (elastic scale-up) starts, by
                # definition, after the cohort's init rendezvous: it is
                # not a party the chief counted, so joining the barrier
                # would poison its arrival count — wait for the marker
                # directly (the Session-level admit handshake then
                # waits for session/init-done the same way)
                self._coord.wait_key('ctrl/init-done/%s' % ns,
                                     timeout_s=120.0)
            else:
                # A worker cannot locally distinguish "fresh cohort
                # member" from "supervised replacement whose cohort
                # already passed this barrier", so it ALWAYS tries the
                # barrier first and consults the init-done marker only
                # between bounded slices. Reading the marker up front
                # would race the chief's stale-marker delete above: on
                # a reused service holding a previous run's marker, a
                # fresh worker arriving before the chief could skip the
                # rendezvous the chief is counting it into and read
                # strategy keys mid-delete. A replacement pays one
                # slice of latency before the marker releases it; a
                # replacement of a worker that died BEFORE the
                # rendezvous simply fills the dead slot (no marker
                # exists yet, and the cohort needs its arrival).
                deadline = time.time() + 120.0
                while True:
                    try:
                        self._coord.barrier(
                            'ctrl/init/%s' % ns,
                            ENV.AUTODIST_NUM_PROCESSES.val,
                            timeout_s=min(10.0, max(
                                1.0, deadline - time.time())))
                        break
                    except TimeoutError:
                        if self._coord.get(
                                'ctrl/init-done/%s' % ns) is not None:
                            break
                        if time.time() >= deadline:
                            raise

    @staticmethod
    def _strategy_is_loose(strategy):
        """True when every synchronizer is relaxed-consistency PS
        (staleness>0 or sync=False): processes then run independent local
        programs and meet only at the coord-service PS (the reference's
        between-graph execution with accumulator num_required=1,
        ps_synchronizer.py:387-458)."""
        syncs = []
        for node in strategy.node_config:
            syncs.extend(node.part_config if node.part_config
                         else [node.synchronizer])
        ps = [s for s in syncs
              if isinstance(s, strategy_base.PSSynchronizer)]
        if len(ps) != len(syncs) or not ps:
            return False
        return all(s.staleness > 0 or not s.sync for s in ps)

    def _setup(self, strategy):
        """Chief-side cluster bring-up + worker launch (reference
        autodist.py:120-128).

        Order matters: workers must be launched BEFORE the blocking
        ``jax.distributed.initialize`` in ``cluster.start()`` — the
        runtime only forms once the full quorum dials in."""
        nodes = list(self._resource_spec.nodes)
        if IS_AUTODIST_CHIEF and len(nodes) > 1 and \
                not self._externally_launched:
            from autodist_tpu.runtime.coordinator import Coordinator
            self._coordinator = Coordinator(
                strategy, self._resource_spec, self._cluster)
            self._coordinator.launch_clients()
            atexit.register(self._coordinator.terminate)

    def _build(self):
        from autodist_tpu.utils import visualization as viz
        self._ensure_control_plane()
        # phase dumps (reference graph_transformer.py:62-90 logs the graph
        # after each transform phase; AUTODIST_DUMP_GRAPHS gates ours)
        dumping = ENV.AUTODIST_DUMP_GRAPHS.val
        if dumping:
            viz.log_text('\n'.join(
                repr(n) for n in self._original_graph_item.graph.nodes),
                '0-original-capture')
        strategy = self._build_or_load_strategy()
        if dumping:
            viz.log_text(strategy, '1-strategy')
        self._setup(strategy)
        from autodist_tpu.runtime.device_resolver import DeviceResolver
        # prune BEFORE the loose/SPMD mode decision: nodes for vars this
        # graph doesn't have must not decide the execution mode
        compiler = strategy_base.StrategyCompiler(self._original_graph_item)
        strategy = compiler.prune(strategy)
        loose = ENV.AUTODIST_NUM_PROCESSES.val > 1 and \
            self._strategy_is_loose(strategy)
        if loose:
            # relaxed-consistency PS: independent local programs + host PS;
            # no global SPMD runtime to form
            import jax
            logging.info('Relaxed-consistency PS strategy: loose '
                         'multi-process mode (local mesh + coord-service '
                         'PS data plane)')
            devices = jax.local_devices()
        else:
            self._cluster.start()
            devices = None  # mesh_from_strategy uses the global view
        resolver = None if loose else DeviceResolver(self._resource_spec)
        compiled = self._compile_strategy(strategy, resolver=resolver,
                                          compiler=compiler)
        if resolver is not None and not self._resource_spec.mesh_hint:
            # the resolved replica list decides the mesh's device order
            # and subset (reference resolver.py:47-67 feeds TF placement)
            sel = resolver.jax_devices_for(compiled.graph_config.replicas)
            if sel is not None:
                devices = sel
        mesh = mesh_from_strategy(compiled, self._resource_spec,
                                  devices=devices)
        if dumping:
            viz.log_text(compiled, '2-compiled-strategy')
        plan = ExecutionPlan(compiled, self._original_graph_item, mesh,
                             loose=loose,
                             topology=self._resource_spec.topology)
        described = plan.describe()
        logging.info(described)
        if dumping:
            viz.log_text(described, '3-execution-plan')
        self._transformed = (compiled, mesh, plan)
        self._built = True

    def is_built(self):
        return self._built

    # -- execution ---------------------------------------------------------
    def create_distributed_session(self):
        """Create the distributed Session (reference autodist.py:191-198)."""
        if not self.is_built():
            self._build()
        _, _, plan = self._transformed
        self._session = Session(self._original_graph_item, plan,
                                cluster=self._cluster, coord=self._coord)
        atexit.register(self._session.close)
        return self._session

    def function(self, fn):
        """TF2-style wrapper (reference autodist.py:269-289): ndarray args
        become placeholders (first dim batch-polymorphic), the traced
        fetches run through a distributed session on every call."""
        def wrapper(*args, **kwargs):
            key = id(fn)
            if key not in self._fn_cache:
                # the entry holds a strong ref to fn: id() stays unique
                # for as long as the cache key exists (no id-reuse alias)
                self._fn_cache[key] = (fn,
                                       self._build_fn(fn, *args, **kwargs))
            return self._fn_cache[key][1](*args, **kwargs)
        return wrapper

    def _build_fn(self, fn, *args, **kwargs):
        # Later functions (session already live) extend the SAME graph and
        # share the session; the strategy was built from the variables seen
        # at first build, so a later trace may reuse variables but not
        # introduce new ones (the strategy has no node_config for them).
        # Snapshot FIRST (before placeholder creation) so a rejected trace
        # rolls back completely — orphan nodes would trip the mutation
        # guard and orphan variables break var-state iteration.
        graph = self._original_graph_item.graph
        extending = self._session is not None
        nodes_before = len(graph.nodes)
        vars_before = set(graph.variables)
        pairs_before = dict(graph.grad_target_pairs)
        opts_before = len(graph.optimizers)
        savers_before = len(graph.savers)
        ph_index = {}
        args_ph, kwargs_ph = [], {}
        for i, a in enumerate(args):
            if isinstance(a, np.ndarray):
                ph = fe.Placeholder((None,) + a.shape[1:],
                                    a.dtype, name='arg%d' % i)
                ph_index[ph] = i
                args_ph.append(ph)
            else:
                args_ph.append(a)
        for k, v in kwargs.items():
            if isinstance(v, np.ndarray):
                ph = fe.Placeholder((None,) + v.shape[1:], v.dtype,
                                    name='kwarg_%s' % k)
                ph_index[ph] = k
                kwargs_ph[k] = ph
            else:
                kwargs_ph[k] = v
        def _rollback():
            del graph.nodes[nodes_before:]
            for name in set(graph.variables) - vars_before:
                del graph.variables[name]
            graph.grad_target_pairs = pairs_before
            del graph.optimizers[opts_before:]
            # a Saver constructed inside a failed trace references
            # rolled-back variables — drop it with the trace
            del graph.savers[savers_before:]

        try:
            with graph:
                fetches = fn(*args_ph, **kwargs_ph)
        except Exception:
            # a partially-traced function must not poison the shared
            # graph: orphan nodes trip the mutation guard (extending) or
            # leave duplicate-variable landmines for a retried first trace
            _rollback()
            raise
        if extending:
            new_vars = set(graph.variables) - vars_before
            if new_vars:
                _rollback()
                raise ValueError(
                    "a later 'autodist.function' created new variables %s "
                    "after the strategy was built; create all variables "
                    "under the first traced function (or one scope) so "
                    "the strategy covers them" % sorted(new_vars))
            session = self._session
            session.refresh_mutation_guard()
        else:
            session = self.create_distributed_session()

        def run_fn(*args, **kwargs):
            feed = {}
            for ph, idx in ph_index.items():
                feed[ph] = args[idx] if isinstance(idx, int) \
                    else kwargs[idx]
            return session.run(fetches, feed)
        return run_fn
