"""User-facing engine: the :class:`AutoDist` object.

Reference parity (``autodist/autodist.py:297-322``): construct with a
resource-spec YAML + a strategy builder; capture the model under
``.scope()``; then either ``create_distributed_session()`` (TF1-style) or
``.function()`` (TF2-style). Chief/worker identity comes from the
``AUTODIST_WORKER`` env flag (autodist.py:40-41): the chief builds and
serializes the strategy, workers deserialize it by ``AUTODIST_STRATEGY_ID``
(autodist.py:100-109) and every process independently lowers it
(docs/design/architecture.rst:43-48).
"""
import atexit
import os

import numpy as np

from autodist_tpu.const import ENV
from autodist_tpu.frontend import graph as fe
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.parallel.mesh import mesh_from_strategy
from autodist_tpu.parallel.plan import ExecutionPlan
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.runtime.cluster import Cluster
from autodist_tpu.runtime.session import Session
from autodist_tpu.strategy import base as strategy_base
from autodist_tpu.strategy.builders import PSLoadBalancing
from autodist_tpu.utils import logging

IS_AUTODIST_WORKER = bool(ENV.AUTODIST_WORKER.val)
IS_AUTODIST_CHIEF = not IS_AUTODIST_WORKER

_DEFAULT_AUTODIST = {}


def set_default_autodist(o):
    """Register the process's AutoDist instance (one per process)."""
    if os.getpid() in _DEFAULT_AUTODIST:
        raise NotImplementedError(
            'Currently only one AutoDist instance is allowed in one process.')
    _DEFAULT_AUTODIST[os.getpid()] = o


def get_default_autodist():
    return _DEFAULT_AUTODIST.get(os.getpid(), None)


def _default_resource_info():
    """Single-node spec from the locally visible jax devices."""
    import jax
    devs = jax.local_devices()
    accel = [d.id for d in devs if d.platform not in ('cpu',)]
    node = {'address': 'localhost', 'chief': True, 'cpus': [0],
            'network_bandwidth': 100}
    if accel:
        node['tpus'] = accel
    else:
        node['gpus'] = list(range(len(devs)))  # virtual CPU devices
    return {'nodes': [node]}


class AutoDist:
    """Distributed-training engine with minimal-code-change ergonomics.

    Args:
        resource_spec_file: path to a resource spec YAML (reference format,
            plus optional ``tpus:`` / ``mesh:`` keys). Defaults to a
            single-node spec over all local devices.
        strategy_builder: a StrategyBuilder (default PSLoadBalancing, as in
            the reference autodist.py:70).
    """

    def __init__(self, resource_spec_file=None, strategy_builder=None,
                 resource_info=None):
        set_default_autodist(self)
        if resource_spec_file is not None:
            self._resource_spec = ResourceSpec(
                resource_file=resource_spec_file)
        else:
            self._resource_spec = ResourceSpec(
                resource_info=resource_info or _default_resource_info())
        self._strategy_builder = strategy_builder or PSLoadBalancing()
        self._original_graph_item = None
        self._transformed = None      # (strategy, mesh, plan)
        self._session = None
        self._cluster = Cluster(self._resource_spec)
        self._built = False
        # ad.function state
        self._fn_cache = {}

    # -- capture -----------------------------------------------------------
    def scope(self):
        """Context manager capturing the code block to be distributed
        (reference autodist.py:309-322)."""
        self._original_graph_item = GraphItem(graph=fe.Graph())
        return self._original_graph_item.graph

    # -- strategy ----------------------------------------------------------
    def build_strategy(self):
        """Build the Strategy for the captured graph (autodist.py:91-98)."""
        return self._strategy_builder.build(
            self._original_graph_item, self._resource_spec)

    def _build_or_load_strategy(self):
        self._original_graph_item.prepare()
        if IS_AUTODIST_CHIEF:
            s = self.build_strategy()
            s.serialize()
        else:
            strategy_id = ENV.AUTODIST_STRATEGY_ID.val
            assert strategy_id, \
                'Worker process needs AUTODIST_STRATEGY_ID set'
            s = strategy_base.Strategy.deserialize(strategy_id)
        return s

    def _compile_strategy(self, strategy):
        logging.debug('Raw strategy: %s', strategy)
        compiled = strategy_base.StrategyCompiler(self._original_graph_item) \
            .compile(strategy)
        logging.info('Compiled strategy: %s', compiled)
        return compiled

    def _setup(self, strategy):
        """Chief-side cluster bring-up + worker launch (reference
        autodist.py:120-128).

        Order matters: workers must be launched BEFORE the blocking
        ``jax.distributed.initialize`` in ``cluster.start()`` — the
        runtime only forms once the full quorum dials in. The chief also
        claims its own identity (process 0 of len(nodes)) so start()
        actually initializes multi-process mode."""
        nodes = list(self._resource_spec.nodes)
        if IS_AUTODIST_CHIEF and len(nodes) > 1:
            os.environ.setdefault(ENV.AUTODIST_NUM_PROCESSES.name,
                                  str(len(nodes)))
            os.environ.setdefault(ENV.AUTODIST_PROCESS_ID.name, '0')
            from autodist_tpu.runtime.coordinator import Coordinator
            self._coordinator = Coordinator(
                strategy, self._resource_spec, self._cluster)
            self._coordinator.launch_clients()
            atexit.register(self._coordinator.terminate)
        self._cluster.start()

    def _build(self):
        strategy = self._build_or_load_strategy()
        self._setup(strategy)
        compiled = self._compile_strategy(strategy)
        mesh = mesh_from_strategy(compiled, self._resource_spec)
        plan = ExecutionPlan(compiled, self._original_graph_item, mesh)
        logging.info(plan.describe())
        self._transformed = (compiled, mesh, plan)
        self._built = True

    def is_built(self):
        return self._built

    # -- execution ---------------------------------------------------------
    def create_distributed_session(self):
        """Create the distributed Session (reference autodist.py:191-198)."""
        if not self.is_built():
            self._build()
        _, _, plan = self._transformed
        self._session = Session(self._original_graph_item, plan,
                                cluster=self._cluster)
        atexit.register(self._session.close)
        return self._session

    def function(self, fn):
        """TF2-style wrapper (reference autodist.py:269-289): ndarray args
        become placeholders (first dim batch-polymorphic), the traced
        fetches run through a distributed session on every call."""
        def wrapper(*args, **kwargs):
            key = id(fn)
            if key not in self._fn_cache:
                if self._fn_cache:
                    raise NotImplementedError(
                        "AutoDist currently only stably supports one "
                        "'autodist.function' across the scope.")
                self._fn_cache[key] = self._build_fn(fn, *args, **kwargs)
            return self._fn_cache[key](*args, **kwargs)
        return wrapper

    def _build_fn(self, fn, *args, **kwargs):
        ph_index = {}
        args_ph, kwargs_ph = [], {}
        for i, a in enumerate(args):
            if isinstance(a, np.ndarray):
                ph = fe.Placeholder((None,) + a.shape[1:],
                                    a.dtype, name='arg%d' % i)
                ph_index[ph] = i
                args_ph.append(ph)
            else:
                args_ph.append(a)
        for k, v in kwargs.items():
            if isinstance(v, np.ndarray):
                ph = fe.Placeholder((None,) + v.shape[1:], v.dtype,
                                    name='kwarg_%s' % k)
                ph_index[ph] = k
                kwargs_ph[k] = ph
            else:
                kwargs_ph[k] = v
        with self._original_graph_item.graph:
            fetches = fn(*args_ph, **kwargs_ph)
        session = self.create_distributed_session()

        def run_fn(*args, **kwargs):
            feed = {}
            for ph, idx in ph_index.items():
                feed[ph] = args[idx] if isinstance(idx, int) \
                    else kwargs[idx]
            return session.run(fetches, feed)
        return run_fn
