"""Optimizers for the symbolic frontend, backed by optax.

The reference monkey-patches every TF ``OptimizerV1/V2`` subclass to capture
constructor args and grad→target pairs (reference ``autodist/patch.py:80-88``,
``autodist/graph_item.py:73-109``) so the partitioner can *recreate* the
optimizer per variable shard (``autodist/kernel/partitioner.py:570-573``).

The TPU-native design needs no patching: optimizers are explicit objects
whose slot state is a pytree threaded through the jitted step. Capture is
structural — constructing an optimizer registers ``(class, args, kwargs)``
on the active graph, and ``apply_gradients`` records grad→target pairs —
and per-shard recreation is free because optax transforms are applied
per-leaf.
"""
import itertools

import jax.numpy as jnp
import optax

from autodist_tpu.frontend import graph as fe

_UID = itertools.count()


class Optimizer:
    """Wraps an optax GradientTransformation, applied per variable leaf.

    Per-leaf (rather than whole-pytree) application is what lets the
    strategy layer shard each variable's slot state with the same
    PartitionSpec as the variable itself (ZeRO-style PS realization).
    """

    # Optimizers with one of the service's update rules (sgd/momentum,
    # adam, adagrad — coord_service BSTEP) publish
    # ``{'rule': <name>, 'params': [<scalar hyperparameters>]}`` here so
    # loose-mode PS sessions can run the update step ON the PS with
    # shared slot state (the reference re-creates the user's optimizer
    # over PS-resident variables, kernel/partitioner.py:570-573);
    # None = PS-side apply unsupported, worker-local slots are used.
    ps_step_params = None

    # Row-lazy update (LazyAdam/LazyMomentum): for sparse-read 2-D
    # variables (embedding tables), apply the update ONLY to rows whose
    # gradient is nonzero, keeping untouched rows — weights AND slot
    # state — bit-stable. Stateful optimizers otherwise densify
    # embedding deltas after the first step (decaying momentum / Adam
    # moments update every row every step), which defeats the loose-
    # mode row-sparse PS push (session._push_ps_deltas).
    lazy_rows = False

    def __init__(self, tx, name=None, _capture=None):
        self.uid = 'opt_%d' % next(_UID)
        self.tx = tx
        self.name = name or type(self).__name__
        g = fe.get_default_graph()
        g.optimizers.append(
            _capture or (type(self).__name__, (), {}))

    # -- symbolic API ------------------------------------------------------
    def apply_gradients(self, grads_and_vars):
        """Create the train-op node (records grad→target pairs)."""
        return fe.ApplyGradients(self, list(grads_and_vars))

    def minimize(self, loss, var_list=None):
        if var_list is None:
            var_list = [v for v in fe.get_default_graph().variables.values()
                        if v.trainable]
        grads = fe.gradients(loss, var_list)
        return self.apply_gradients(zip(grads, var_list))

    # -- state management (called by the Session / compiler) --------------
    def init_slot_state(self, variables, var_values):
        """Per-variable optax slot state: {var name: leaf state}."""
        return {v.name: self.tx.init(jnp.asarray(var_values[v.name]))
                for v in variables}

    def _apply(self, grads_and_vars, env):
        """Evaluate the update inside the step trace. Returns new values.

        Gradients arriving as :class:`~autodist_tpu.parallel.plan.
        ShardedGrad` update only the local (ZeRO) shard of the variable and
        its slot state; the session's out-shardings keep the result
        distributed.
        """
        from autodist_tpu.parallel.plan import ShardedGrad
        slots = dict(env.opt_state.get(self.uid, {}))
        new_values = {}
        for grad, var in grads_and_vars:
            state = slots[var.name]
            if getattr(grad, 'is_update_shard', False):
                # cross-replica weight-update sharding: the grad is
                # this replica's 1/n flat shard of the bucket
                # reduce-scatter; slice the matching param shard (a
                # local dynamic-slice — slots are already stored as
                # flat shards), run the fused shard-local update, and
                # hand the updated shard back — ApplyGradients
                # evaluation re-gathers whole buckets afterwards.
                value = grad.slice_param(env.var_values[var.name])
                new_shard, slots[var.name] = self.shard_update(
                    grad.value, state, value,
                    axis_name=grad.axis_name)
                new_values[var] = grad.with_value(new_shard)
                continue
            if isinstance(grad, ShardedGrad):
                value = env.var_shards[var.name]
                update, new_state = self.tx.update(grad.value, state, value)
            else:
                value = env.var_values[var.name]
                if self.lazy_rows and getattr(var, 'sparse_read',
                                              False) and \
                        getattr(grad, 'ndim', 0) == 2 and \
                        tuple(grad.shape) == tuple(value.shape):
                    new_values[var], slots[var.name] = \
                        self._lazy_row_update(grad, state, value)
                    continue
                update, new_state = self.tx.update(grad, state, value)
            new_values[var] = value + update
            slots[var.name] = new_state
        env.opt_updates[self.uid] = slots
        return new_values

    def shard_update(self, grad, state, value, axis_name=None):
        """Fused optimizer step over ONE weight-update shard: the 1/n
        flat gradient shard, the matching shard-resident slot state
        and param shard (cross-replica weight-update sharding,
        parallel/plan.py).

        The default applies the optimizer's own transform to the
        shard, which is EXACT for elementwise updates — every built-in
        optimizer here except LAMB (SGD/momentum, Adam(W), Adagrad,
        RMSProp, Adadelta, Adamax, Nadam, Ftrl) updates each element
        from that element's grad/slot/param alone, so sharding commutes
        with the update bit-for-bit given the same reduced gradient.
        Optimizers with cross-element coupling must override:
        :class:`LAMB` computes its per-variable trust-ratio norms with
        a ``psum`` over the shards. Custom non-elementwise transforms
        that cannot be corrected this way should keep
        ``weight_update_sharding='never'``.
        """
        update, new_state = self.tx.update(grad, state, value)
        return value + update, new_state

    def _lazy_row_update(self, grad, state, value):
        """Row-masked update: rows with an all-zero gradient keep their
        weights and (same-shaped) slot state bit-identical; scalar
        slots (e.g. the Adam step count) advance globally — the same
        shared-t semantics as TF's LazyAdam."""
        import jax
        mask = jnp.any(grad != 0, axis=1, keepdims=True)
        update, new_state = self.tx.update(grad, state, value)

        def keep_untouched(new, old):
            if hasattr(new, 'shape') and \
                    tuple(new.shape) == tuple(value.shape):
                return jnp.where(mask, new, old)
            return new

        return (jnp.where(mask, value + update, value),
                jax.tree.map(keep_untouched, new_state, state))


class SGD(Optimizer):
    """Plain / momentum / Nesterov SGD (reference test matrix: GradientDescent,
    Momentum; tests/test_graph_item.py:55-86)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, nesterov=False,
                 name=None):
        super().__init__(
            optax.sgd(learning_rate, momentum=momentum or None,
                      nesterov=nesterov),
            name, _capture=('SGD', (learning_rate,),
                            {'momentum': momentum, 'nesterov': nesterov}))
        if not nesterov and isinstance(learning_rate, (int, float)):
            # BSTEP implements vel = m*vel + g; w -= lr*vel (optax.sgd's
            # trace form); nesterov variants stay worker-local
            self.ps_step_params = {
                'rule': 'sgd',
                'params': [float(learning_rate), float(momentum)]}


GradientDescent = SGD


class Momentum(SGD):
    def __init__(self, learning_rate=0.01, momentum=0.9, **kw):
        super().__init__(learning_rate, momentum=momentum, **kw)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta_1=0.9, beta_2=0.999,
                 epsilon=1e-7, name=None):
        super().__init__(
            optax.adam(learning_rate, b1=beta_1, b2=beta_2, eps=epsilon),
            name, _capture=('Adam', (learning_rate,),
                            {'beta_1': beta_1, 'beta_2': beta_2,
                             'epsilon': epsilon}))
        if isinstance(learning_rate, (int, float)):
            # BSTEP adam matches optax.adam (bias-corrected moments,
            # eps outside the sqrt); the step index t is PS-resident
            # and shared, like the moments
            self.ps_step_params = {
                'rule': 'adam',
                'params': [float(learning_rate), float(beta_1),
                           float(beta_2), float(epsilon)]}


class LazyAdam(Optimizer):
    """Adam that updates ONLY rows with nonzero gradient on sparse-read
    (embedding) variables — untouched rows keep weights and moments
    bit-stable, so loose-mode deltas stay row-sparse and the PS push
    ships O(batch) rows instead of the whole table. Dense variables
    get plain Adam. The step count (bias-correction t) is global, like
    TF's ``tf.keras.optimizers.LazyAdam``. No PS-side shared-slot rule:
    the service's BSTEP adam is dense by definition."""

    lazy_rows = True

    def __init__(self, learning_rate=0.001, beta_1=0.9, beta_2=0.999,
                 epsilon=1e-7, name=None):
        super().__init__(
            optax.adam(learning_rate, b1=beta_1, b2=beta_2, eps=epsilon),
            name, _capture=('LazyAdam', (learning_rate,),
                            {'beta_1': beta_1, 'beta_2': beta_2,
                             'epsilon': epsilon}))


class LazyMomentum(Optimizer):
    """Momentum SGD with row-lazy updates on sparse-read variables:
    a row's velocity decays (and its weight moves) only on steps where
    that row's gradient is nonzero. See :class:`LazyAdam`."""

    lazy_rows = True

    def __init__(self, learning_rate=0.01, momentum=0.9, name=None):
        super().__init__(
            optax.sgd(learning_rate, momentum=momentum or None),
            name, _capture=('LazyMomentum', (learning_rate,),
                            {'momentum': momentum}))


class AdamW(Optimizer):
    def __init__(self, learning_rate=0.001, weight_decay=0.01, beta_1=0.9,
                 beta_2=0.999, epsilon=1e-7, name=None):
        super().__init__(
            optax.adamw(learning_rate, b1=beta_1, b2=beta_2, eps=epsilon,
                        weight_decay=weight_decay),
            name, _capture=('AdamW', (learning_rate,),
                            {'weight_decay': weight_decay}))


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, initial_accumulator_value=0.1,
                 epsilon=1e-7, name=None):
        super().__init__(
            optax.adagrad(learning_rate,
                          initial_accumulator_value=initial_accumulator_value,
                          eps=epsilon),
            name, _capture=('Adagrad', (learning_rate,), {}))
        if isinstance(learning_rate, (int, float)):
            self.ps_step_params = {
                'rule': 'adagrad',
                'params': [float(learning_rate), float(epsilon),
                           float(initial_accumulator_value)]}


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.9, momentum=0.0,
                 epsilon=1e-7, name=None):
        super().__init__(
            optax.rmsprop(learning_rate, decay=rho, eps=epsilon,
                          momentum=momentum or None),
            name, _capture=('RMSProp', (learning_rate,),
                            {'rho': rho, 'momentum': momentum}))


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-7,
                 name=None):
        super().__init__(
            optax.adadelta(learning_rate, rho=rho, eps=epsilon),
            name, _capture=('Adadelta', (learning_rate,), {}))


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta_1=0.9, beta_2=0.999,
                 epsilon=1e-7, name=None):
        super().__init__(
            optax.adamax(learning_rate, b1=beta_1, b2=beta_2, eps=epsilon),
            name, _capture=('Adamax', (learning_rate,), {}))


class Nadam(Optimizer):
    """Adam with Nesterov momentum (reference test matrix: nadam)."""

    def __init__(self, learning_rate=0.001, beta_1=0.9, beta_2=0.999,
                 epsilon=1e-7, name=None):
        super().__init__(
            optax.nadam(learning_rate, b1=beta_1, b2=beta_2, eps=epsilon),
            name, _capture=('Nadam', (learning_rate,),
                            {'beta_1': beta_1, 'beta_2': beta_2}))


def _ftrl(learning_rate, learning_rate_power, initial_accumulator_value,
          l1, l2, beta):
    """FTRL-proximal (TF keras Ftrl semantics); optax has no ftrl."""
    import jax

    def init_fn(params):
        return jax.tree.map(
            lambda p: (jnp.full_like(p, initial_accumulator_value),
                       jnp.zeros_like(p)), params,
            is_leaf=lambda x: hasattr(x, 'shape'))

    def _leaf(grad, state, param):
        n, z = state
        n_new = n + grad * grad
        p = -learning_rate_power
        pow_old, pow_new = n ** p, n_new ** p
        sigma = (pow_new - pow_old) / learning_rate
        z_new = z + grad - sigma * param
        denom = (beta + pow_new) / learning_rate + 2.0 * l2
        w_new = jnp.where(
            jnp.abs(z_new) <= l1, jnp.zeros_like(z_new),
            -(z_new - jnp.sign(z_new) * l1) / denom)
        return w_new - param, (n_new, z_new)

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError('ftrl requires params')
        flat_u, tree = jax.tree.flatten(updates)
        flat_s = tree.flatten_up_to(state)
        flat_p = jax.tree.leaves(params)
        out = [_leaf(u, s, p) for u, s, p in zip(flat_u, flat_s, flat_p)]
        return (tree.unflatten([o[0] for o in out]),
                tree.unflatten([o[1] for o in out]))

    return optax.GradientTransformation(init_fn, update_fn)


class Ftrl(Optimizer):
    """FTRL-proximal (reference test matrix: ftrl); supports the l1
    shrinkage that zeroes small weights."""

    def __init__(self, learning_rate=0.001, learning_rate_power=-0.5,
                 initial_accumulator_value=0.1,
                 l1_regularization_strength=0.0,
                 l2_regularization_strength=0.0, beta=0.0, name=None):
        super().__init__(
            _ftrl(learning_rate, learning_rate_power,
                  initial_accumulator_value, l1_regularization_strength,
                  l2_regularization_strength, beta),
            name, _capture=('Ftrl', (learning_rate,),
                            {'l1': l1_regularization_strength,
                             'l2': l2_regularization_strength}))


class LAMB(Optimizer):
    """Layer-wise adaptive optimizer used by the BERT-large benchmark."""

    def __init__(self, learning_rate=0.001, weight_decay=0.0, beta_1=0.9,
                 beta_2=0.999, epsilon=1e-6, name=None):
        super().__init__(
            optax.lamb(learning_rate, b1=beta_1, b2=beta_2, eps=epsilon,
                       weight_decay=weight_decay),
            name, _capture=('LAMB', (learning_rate,),
                            {'weight_decay': weight_decay}))
        self._hp = {'learning_rate': learning_rate,
                    'weight_decay': weight_decay, 'beta_1': beta_1,
                    'beta_2': beta_2, 'epsilon': epsilon}

    def shard_update(self, grad, state, value, axis_name=None):
        """Fused shard-local LAMB step (weight-update sharding).

        LAMB is the one built-in with cross-element coupling: its
        trust ratio scales each variable's update by
        ``||param|| / ||adam update||`` over the WHOLE variable, so a
        naive per-shard application would use shard-local norms and
        diverge from the replicated update. The fused step runs the
        elementwise Adam half on the shard, then computes both norms
        with a ``psum`` of the per-shard squared sums across the data
        axis — the padded tail contributes exactly zero (zero param,
        zero moments, zero grad), so the norms equal the full-tensor
        norms up to summation re-association, and the sharded update
        matches the replicated one within f32 re-association ulps.
        """
        chain = tuple(state) if isinstance(state, (tuple, list)) \
            else (state,)
        idx = next((i for i, s in enumerate(chain)
                    if hasattr(s, 'mu') and hasattr(s, 'nu')), None)
        if idx is None or axis_name is None:
            return super().shard_update(grad, state, value,
                                        axis_name=axis_name)
        import jax
        hp = self._hp
        adam = optax.scale_by_adam(b1=hp['beta_1'], b2=hp['beta_2'],
                                   eps=hp['epsilon'])
        u, new_adam = adam.update(grad, chain[idx], value)
        if hp['weight_decay']:
            u = u + hp['weight_decay'] * value
        p_norm = jnp.sqrt(jax.lax.psum(jnp.sum(value * value),
                                       axis_name))
        u_norm = jnp.sqrt(jax.lax.psum(jnp.sum(u * u), axis_name))
        # optax scale_by_trust_ratio semantics: zero param or zero
        # update -> ratio 1
        ratio = jnp.where(p_norm == 0., 1.,
                          jnp.where(u_norm == 0., 1., p_norm / u_norm))
        new_state = chain[:idx] + (new_adam,) + chain[idx + 1:]
        if not isinstance(state, (tuple, list)):
            new_state = new_state[0]
        return value - hp['learning_rate'] * ratio * u, new_state
