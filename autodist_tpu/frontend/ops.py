"""Lifted numeric ops for the symbolic frontend.

Any ``jnp`` function can be lifted with :func:`lift`; the common ones used
by the reference's example models (reduce_mean, square, matmul, embedding
lookups, losses — see /root/reference/examples and tests/integration/cases)
are exported directly.

``embedding_lookup`` additionally marks its table Variable as
``sparse_read`` — the analogue of the reference's IndexedSlices-gradient
detection that strategy builders use to route sparse variables to PS
(parallax_strategy.py:38-70, partitioner.py:660-684).
"""
import jax
import jax.numpy as jnp

from autodist_tpu.frontend import graph as fe


def lift(fn):
    """Lift a jax-traceable function to operate on SymTensors."""
    def lifted(*args, **kwargs):
        return fe.Op(fn, list(args), kwargs)
    lifted.__name__ = getattr(fn, '__name__', 'lifted')
    return lifted


def _sym(fn, *args, **kwargs):
    return fe.Op(fn, list(args), kwargs)


def constant(value, name=None):
    return fe.Const(value, name=name)


# Elementwise / reductions -------------------------------------------------
def square(x):
    return _sym(jnp.square, x)


def sqrt(x):
    return _sym(jnp.sqrt, x)


def exp(x):
    return _sym(jnp.exp, x)


def log(x):
    return _sym(jnp.log, x)


def tanh(x):
    return _sym(jnp.tanh, x)


def sigmoid(x):
    return _sym(jax.nn.sigmoid, x)


def relu(x):
    return _sym(jax.nn.relu, x)


def softmax(x, axis=-1):
    return _sym(jax.nn.softmax, x, axis=axis)


def abs(x):  # noqa: A001 - mirrors tf.abs
    return _sym(jnp.abs, x)


def reduce_mean(x, axis=None):
    return _sym(jnp.mean, x, axis=axis)


def reduce_sum(x, axis=None):
    return _sym(jnp.sum, x, axis=axis)


def reduce_max(x, axis=None):
    return _sym(jnp.max, x, axis=axis)


def argmax(x, axis=-1):
    return _sym(jnp.argmax, x, axis=axis)


def cast(x, dtype):
    return _sym(lambda v: jnp.asarray(v, dtype=dtype), x)


def reshape(x, shape):
    return _sym(jnp.reshape, x, shape)


def transpose(x, axes=None):
    return _sym(jnp.transpose, x, axes=axes)


def concat(xs, axis=0):
    return fe.Op(lambda *vs: jnp.concatenate(vs, axis=axis), list(xs))


def stack(xs, axis=0):
    return fe.Op(lambda *vs: jnp.stack(vs, axis=axis), list(xs))


def matmul(a, b):
    return _sym(jnp.matmul, a, b)


def one_hot(x, depth):
    return _sym(jax.nn.one_hot, x, depth)


def squeeze(x, axis=None):
    return _sym(jnp.squeeze, x, axis=axis)


def expand_dims(x, axis):
    return _sym(jnp.expand_dims, x, axis)


# Losses -------------------------------------------------------------------
def sigmoid_cross_entropy_with_logits(labels, logits):
    def fn(labels, logits):
        return jnp.maximum(logits, 0) - logits * labels + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return _sym(fn, labels, logits)


def sparse_softmax_cross_entropy_with_logits(labels, logits):
    def fn(labels, logits):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(
            logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return _sym(fn, labels, logits)


def softmax_cross_entropy_with_logits(labels, logits):
    def fn(labels, logits):
        return -jnp.sum(labels * jax.nn.log_softmax(logits, -1), axis=-1)
    return _sym(fn, labels, logits)


# Embeddings ---------------------------------------------------------------
def gather(params, indices, axis=0):
    """Index gather; marks a Variable source as sparse-read so strategy
    builders can treat its gradient as sparse (reference: IndexedSlices
    through ``embedding_lookup_v2``, partitioner.py:576-602)."""
    node = _sym(lambda p, i: jnp.take(p, i.astype(jnp.int32), axis=axis),
                params, indices)
    if isinstance(params, fe.Variable):
        params.sparse_read = True
        if axis == 0 and isinstance(indices, fe.SymTensor):
            params.lookup_ids.append(indices)
            params.lookup_ops.append(node)
    return node


def embedding_lookup(params, ids):
    """Row gather from an embedding table Variable."""
    return gather(params, ids, axis=0)


# Convolutions / pooling (CNN-class user models; reference captures
# arbitrary tf.nn graphs — cases c1/c5 are Keras CNN/dense stacks) -------
def conv2d(x, filters, strides=1, padding='SAME'):
    """NHWC conv with HWIO filters (the TF default layout the reference's
    models use; also XLA's preferred TPU layout)."""
    s = (strides, strides) if isinstance(strides, int) else tuple(strides)

    def fn(x, w):
        return jax.lax.conv_general_dilated(
            x, w, window_strides=s, padding=padding,
            dimension_numbers=('NHWC', 'HWIO', 'NHWC'))
    return _sym(fn, x, filters)


def bias_add(x, b):
    return _sym(lambda x, b: x + b, x, b)


def _pool_dims(size, strides):
    k = (size, size) if isinstance(size, int) else tuple(size)
    s = k if strides is None else (
        (strides, strides) if isinstance(strides, int) else tuple(strides))
    return k, s


def max_pool(x, size=2, strides=None, padding='VALID'):
    k, s = _pool_dims(size, strides)

    def fn(x):
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            window_dimensions=(1,) + k + (1,),
            window_strides=(1,) + s + (1,),
            padding=padding)
    return _sym(fn, x)


def avg_pool(x, size=2, strides=None, padding='VALID'):
    k, s = _pool_dims(size, strides)

    def fn(x):
        dims, strides_ = (1,) + k + (1,), (1,) + s + (1,)
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, window_dimensions=dims,
            window_strides=strides_, padding=padding)
        if padding == 'VALID':
            return summed / (k[0] * k[1])
        # SAME: TF semantics divide by the count of VALID cells in each
        # window (padded cells excluded), not the full window size
        counts = jax.lax.reduce_window(
            jnp.ones_like(x), 0.0, jax.lax.add, window_dimensions=dims,
            window_strides=strides_, padding=padding)
        return summed / counts
    return _sym(fn, x)


# Control flow -------------------------------------------------------------
def while_loop(cond_fn, body_fn, init, max_iters=None):
    """Lifted ``lax.while_loop`` over symbolic carries.

    The condition/body are jax-level functions applied to traced values —
    the compiler-friendly replacement for the reference's TF v1 while_loop
    handling (case c4, control-flow contexts in replicator.py:92-103).

    With ``max_iters`` (a static trip bound), the loop lowers to a
    bounded ``lax.scan`` whose body is gated by ``cond_fn`` via
    ``lax.cond`` — semantically identical for any loop that terminates
    within the bound, and REVERSE-DIFFERENTIABLE, restoring the
    reference's ability to train through ``tf.while_loop``
    (cases/c4.py:24-34). Without it, the loop is a true
    ``lax.while_loop``: unbounded, forward-only.
    """
    if max_iters is None:
        def fn(*vals):
            return jax.lax.while_loop(cond_fn, body_fn, tuple(vals))
        return fe.Op(fn, list(init))

    def fn(*vals):
        def step(carry, _):
            keep_going = cond_fn(carry)
            new = jax.lax.cond(keep_going, body_fn, lambda c: c, carry)
            return new, None
        out, _ = jax.lax.scan(step, tuple(vals), None,
                              length=int(max_iters))
        return out
    return fe.Op(fn, list(init))


def cond(pred, true_fn, false_fn, operands):
    def fn(p, *vals):
        return jax.lax.cond(p, true_fn, false_fn, *vals)
    return fe.Op(fn, [pred] + list(operands))


def scan(body_fn, init, xs):
    def fn(c, x):
        return jax.lax.scan(body_fn, c, x)
    return _sym(fn, init, xs)
