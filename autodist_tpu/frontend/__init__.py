"""frontend subpackage."""
