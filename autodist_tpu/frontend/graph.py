"""Symbolic capture frontend.

The reference captures the user's model as a ``tf.Graph`` plus
monkey-patched optimizer hooks (reference ``autodist/graph_item.py:73-109``,
``autodist/patch.py:80-88``). The TPU-native equivalent cannot lean on TF
graph mode, so this module provides a *minimal symbolic tensor DSL*:

- :class:`Placeholder`, :class:`Variable` reads, :class:`Const` and generic
  lifted-``jnp`` :class:`Op` nodes form a DAG while user code runs inside
  ``ad.scope()``;
- :class:`Gradients` nodes capture ``ad.gradients(loss, vars)`` requests;
- optimizer ``apply_gradients`` creates an :class:`ApplyGradients` train-op
  node and records grad→target pairs on the graph (same bookkeeping the
  reference does via monkey-patching);
- at session time the whole DAG is *interpreted once inside a jax trace*
  (:func:`evaluate`), so the executed artifact is a single fused XLA
  program — graph surgery is replaced by functional re-interpretation.

Everything here is build-time only; no per-step Python cost beyond the
jitted function dispatch.
"""
import itertools
import threading

import jax
import jax.numpy as jnp
import numpy as np

_GRAPH_STACK = threading.local()


def _stack():
    if not hasattr(_GRAPH_STACK, 'stack'):
        _GRAPH_STACK.stack = []
    return _GRAPH_STACK.stack


def get_default_graph():
    """Return the innermost active Graph, creating a global one if needed."""
    stack = _stack()
    if not stack:
        stack.append(Graph())
    return stack[-1]


class Graph:
    """A captured symbolic program: nodes, variables, grad→target pairs."""

    def __init__(self):
        self._name_counter = itertools.count()
        self.variables = {}            # name -> Variable
        self.nodes = []
        self.grad_target_pairs = {}    # grad node -> Variable
        self.optimizers = []           # captured (class, args, kwargs)
        self.savers = []               # registered Saver objects

    def unique_name(self, prefix):
        return '%s_%d' % (prefix, next(self._name_counter))

    def register_variable(self, var):
        if var.name in self.variables:
            raise ValueError('Duplicate variable name %r' % var.name)
        self.variables[var.name] = var

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()

    def as_default(self):
        return self


class SymTensor:
    """Base class for all symbolic nodes. Supports jnp-style operators."""

    def __init__(self, shape=None, dtype=None, name=None):
        self.graph = get_default_graph()
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name or self.graph.unique_name(type(self).__name__)
        self.graph.nodes.append(self)

    # -- operator sugar ---------------------------------------------------
    def _binop(self, fn, other, reverse=False):
        a, b = (other, self) if reverse else (self, other)
        return Op(fn, [a, b])

    def __add__(self, o):
        return self._binop(jnp.add, o)

    def __radd__(self, o):
        return self._binop(jnp.add, o, True)

    def __sub__(self, o):
        return self._binop(jnp.subtract, o)

    def __rsub__(self, o):
        return self._binop(jnp.subtract, o, True)

    def __mul__(self, o):
        return self._binop(jnp.multiply, o)

    def __rmul__(self, o):
        return self._binop(jnp.multiply, o, True)

    def __truediv__(self, o):
        return self._binop(jnp.divide, o)

    def __rtruediv__(self, o):
        return self._binop(jnp.divide, o, True)

    def __pow__(self, o):
        return self._binop(jnp.power, o)

    def __matmul__(self, o):
        return self._binop(jnp.matmul, o)

    def __rmatmul__(self, o):
        return self._binop(jnp.matmul, o, True)

    def __neg__(self):
        return Op(jnp.negative, [self])

    def __getitem__(self, idx):
        return Op(lambda x: x[idx], [self])

    @property
    def T(self):  # noqa: N802 - numpy-style transpose property
        return Op(jnp.transpose, [self])

    def __repr__(self):
        return '<%s %r shape=%s>' % (type(self).__name__, self.name,
                                     self.shape)


class Placeholder(SymTensor):
    """Feedable input; polymorphic batch dim expressed as None."""

    def __init__(self, shape=None, dtype=jnp.float32, name=None):
        super().__init__(shape, dtype, name)


class Const(SymTensor):
    """Embedded constant value."""

    def __init__(self, value, name=None):
        value = np.asarray(value)
        super().__init__(value.shape, value.dtype, name)
        self.value = value


class Op(SymTensor):
    """Generic lifted op: ``fn(*inputs, **kwargs)`` where inputs may mix
    SymTensors and python literals."""

    def __init__(self, fn, inputs, kwargs=None, name=None):
        super().__init__(None, None, name)
        self.fn = fn
        self.inputs = list(inputs)
        self.kwargs = kwargs or {}


class VariableRead(SymTensor):
    """Read of a Variable's current value at step entry."""

    def __init__(self, variable):
        super().__init__(variable.init_value.shape,
                         variable.init_value.dtype,
                         variable.name + '/read')
        self.variable = variable


class Gradients(SymTensor):
    """``ad.gradients(loss, sources)``: list-valued node.

    Evaluated by re-tracing the loss subgraph as a function of the source
    variables and calling ``jax.grad`` — the functional analogue of the
    reference's reliance on TF's symbolic autodiff.
    """

    def __init__(self, loss, sources, name=None):
        super().__init__(None, None, name)
        self.loss = loss
        self.sources = list(sources)
        self._slices = None

    def __iter__(self):
        if self._slices is None:
            self._slices = [GradientSlice(self, i)
                            for i in range(len(self.sources))]
        return iter(self._slices)

    def __len__(self):
        return len(self.sources)


class GradientSlice(SymTensor):
    """The i-th output of a Gradients node."""

    def __init__(self, grads, index):
        super().__init__(None, None,
                         '%s/grad_%d' % (grads.name, index))
        self.grads = grads
        self.index = index


class ApplyGradients(SymTensor):
    """Train op: applying an optimizer update to variables.

    Mirrors the reference's optimizer-capture: creating this node records
    grad→target pairs on the graph (graph_item.py:93-109) and the optimizer
    spec (graph_item.py:73-90) for the strategy layer to inspect.
    """

    def __init__(self, optimizer, grads_and_vars, name=None):
        super().__init__((), None, name or
                         get_default_graph().unique_name('ApplyGradients'))
        self.optimizer = optimizer
        self.grads_and_vars = list(grads_and_vars)
        g = self.graph
        for grad, var in self.grads_and_vars:
            g.grad_target_pairs[grad] = var


class Variable:
    """A mutable training parameter.

    Not itself a node: arithmetic on it reads the current value via a
    :class:`VariableRead`. State lives in the Session, threaded through the
    jitted step function — the functional replacement for TF resource
    variables.
    """

    def __init__(self, initial_value, name=None, trainable=True,
                 dtype=None):
        self.graph = get_default_graph()
        init = np.asarray(initial_value, dtype=dtype)
        if init.dtype == np.float64:
            init = init.astype(np.float32)  # TPU-native default
        self.init_value = init
        self.name = name or self.graph.unique_name('Variable')
        self.trainable = trainable
        # Set when the variable is consumed by an embedding lookup — the
        # analogue of the reference's IndexedSlices-gradient detection
        # (partitioned_ps_strategy.py / parallax_strategy.py sparse checks).
        self.sparse_read = False
        # The id-tensor nodes of those lookups: lets the sync layer ship
        # (indices, rows) instead of the dense vocab-sized gradient (the
        # IndexedSlices equivalent, reference partitioner.py:660-684).
        # lookup_ops are the gather Op nodes themselves, used to prove the
        # variable has no OTHER (dense) consumers before the sparse wire
        # is allowed — a dense use contributes gradient to rows outside
        # the looked-up set, which the sparse wire would drop.
        self.lookup_ids = []
        self.lookup_ops = []
        self.graph.register_variable(self)
        self._read = None

    @property
    def shape(self):
        return self.init_value.shape

    @property
    def dtype(self):
        return self.init_value.dtype

    @property
    def nbytes(self):
        return int(self.init_value.nbytes)

    def read(self):
        if self._read is None:
            self._read = VariableRead(self)
        return self._read

    # operator sugar delegates to the read node
    def __add__(self, o):
        return self.read() + o

    def __radd__(self, o):
        return o + self.read()

    def __sub__(self, o):
        return self.read() - o

    def __rsub__(self, o):
        return o - self.read()

    def __mul__(self, o):
        return self.read() * o

    def __rmul__(self, o):
        return o * self.read()

    def __truediv__(self, o):
        return self.read() / o

    def __rtruediv__(self, o):
        return o / self.read()

    def __pow__(self, o):
        return self.read() ** o

    def __matmul__(self, o):
        return self.read() @ o

    def __rmatmul__(self, o):
        return o @ self.read()

    def __neg__(self):
        return -self.read()

    def __getitem__(self, idx):
        return self.read()[idx]

    @property
    def T(self):  # noqa: N802
        return self.read().T

    def __repr__(self):
        return '<Variable %r shape=%s dtype=%s>' % (
            self.name, self.shape, self.dtype)


def placeholder(shape=None, dtype=jnp.float32, name=None):
    """Create a feedable input node (parity with tf.placeholder)."""
    return Placeholder(shape, dtype, name)


def gradients(loss, sources):
    """Symbolic gradients of ``loss`` w.r.t. ``sources`` (Variables)."""
    for s in sources:
        if not isinstance(s, Variable):
            raise TypeError('gradients sources must be Variables, got %r'
                            % (s,))
    return Gradients(loss, sources)


# ---------------------------------------------------------------------------
# Evaluation: interpret the DAG inside a jax trace.
# ---------------------------------------------------------------------------

class Env:
    """One evaluation environment: variable values + feeds + memo table."""

    def __init__(self, var_values, feeds, grad_sync_fn=None,
                 opt_state=None, aux_state=None):
        self.var_values = var_values      # {var name: jax value}
        self.feeds = feeds                # {Placeholder node: jax value}
        self.memo = {}
        # Hook applied to the full evaluated gradient list of a Gradients
        # node: ``fn(sources, grads, env) -> synced grads``. The strategy
        # compiler injects per-variable synchronization here (psum /
        # compressor / group-fused collectives / reduce-scatter) when
        # running inside shard_map.
        self.grad_sync_fn = grad_sync_fn
        self.opt_state = opt_state or {}  # {optimizer uid: slot pytree}
        self.aux_state = aux_state or {}  # e.g. compressor residuals
        self.var_shards = {}              # local shards of ZeRO-sharded vars
        self.updates = {}                 # {var name: new value}
        self.opt_updates = {}             # {optimizer uid: new slot pytree}
        self.aux_updates = {}             # {aux key: new value}


def evaluate(node, env):
    """Interpret one node under ``env`` (memoized)."""
    if isinstance(node, Variable):
        node = node.read()
    key = id(node)
    if key in env.memo:
        return env.memo[key]
    out = _eval(node, env)
    env.memo[key] = out
    return out


def _resolve(x, env):
    if isinstance(x, (SymTensor, Variable)):
        return _degrade(evaluate(x, env))
    if isinstance(x, (list, tuple)):
        return type(x)(_resolve(v, env) for v in x)
    return x


def _degrade(val):
    """Materialize framework wrappers before generic jnp ops consume them.

    A ZeRO-sharded gradient (parallel.plan.ShardedGrad) stays a shard on
    the ApplyGradients fast path, but user arithmetic on it (grad-norm
    clipping etc.) needs the full array — gather without disturbing the
    memoized shard."""
    if isinstance(val, list):
        return [_degrade(v) for v in val]
    gather = getattr(val, 'gather', None)
    return gather() if callable(gather) else val


def _eval(node, env):
    if isinstance(node, Placeholder):
        if node not in env.feeds:
            raise KeyError('Placeholder %r was not fed' % node.name)
        return env.feeds[node]
    if isinstance(node, Const):
        return jnp.asarray(node.value)
    if isinstance(node, VariableRead):
        return env.var_values[node.variable.name]
    if isinstance(node, Op):
        args = [_resolve(a, env) for a in node.inputs]
        kwargs = {k: _resolve(v, env) for k, v in node.kwargs.items()}
        return node.fn(*args, **kwargs)
    if isinstance(node, Gradients):
        return _eval_gradients(node, env)
    if isinstance(node, GradientSlice):
        return evaluate(node.grads, env)[node.index]
    if isinstance(node, ApplyGradients):
        return _eval_apply(node, env)
    raise TypeError('Cannot evaluate node %r' % (node,))


def _eval_gradients(node, env):
    names = [v.name for v in node.sources]

    def loss_of(vals):
        sub = dict(env.var_values)
        sub.update(dict(zip(names, vals)))
        sub_env = Env(sub, env.feeds, None, env.opt_state, env.aux_state)
        loss = evaluate(node.loss, sub_env)
        return jnp.asarray(loss, dtype=jnp.float32) \
            if loss.dtype not in (jnp.float32, jnp.float64) else loss

    vals = [env.var_values[n] for n in names]
    loss_val, grads = jax.value_and_grad(loss_of)(vals)
    # Share the forward pass with a direct fetch of the loss node.
    env.memo.setdefault(id(node.loss), loss_val)
    grads = list(grads)
    if env.grad_sync_fn is not None:
        grads = env.grad_sync_fn(node.sources, grads, env)
    return grads


def _eval_apply(node, env):
    gv = []
    for grad, var in node.grads_and_vars:
        gv.append((evaluate(grad, env), var))
    new_values = node.optimizer._apply(gv, env)
    # weight-update-sharded variables come back as UpdateShards (each
    # replica updated its 1/n); re-gather whole buckets at once — one
    # collective per scatter bucket, the gather half of the schedule
    # (parallel.plan.ExecutionPlan.gather_updated_params)
    pending = {var: val for var, val in new_values.items()
               if getattr(val, 'is_update_shard', False)}
    if pending:
        plan = next(iter(pending.values())).plan
        gathered = plan.gather_updated_params(
            {var.name: val for var, val in pending.items()})
        for var in pending:
            new_values[var] = gathered[var.name]
    for var, val in new_values.items():
        env.updates[var.name] = val
    return jnp.zeros((), jnp.int32)  # train-op sentinel value
