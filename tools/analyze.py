"""Repo-wide static analysis CLI — one entry over the four analyzers.

    python tools/analyze.py --all            # everything, exit 0 = clean
    python tools/analyze.py --fence --env    # just those analyzers
    python tools/analyze.py --all --json     # machine-readable report
    python tools/analyze.py --conformance dump.json   # replay a
             # flight-recorder dump through the protocol invariants

Analyzers (autodist_tpu/analysis/, design notes in
docs/design/static-analysis.md):

  protocol   bounded model checking of the control-plane protocol
             (HEAD orderings explore clean; the seeded historical bugs
             must still re-derive as counterexamples)
  fence      coord_service.cc dispatcher fence-coverage + header table
             drift (absorbs tools/check_protocol.py)
  env        AUTODIST_* env reads declared + worker knobs forwarded
  schedule   sync_gradients vs static_collective_schedule emission
             predicates, reshard shape algebra, wire-pricing drift
             (absorbs tools/check_wire_pricing.py)

``--conformance <dump>...`` is the dynamic twin (docs/design/
observability.md): it replays the crash flight recorder's event trace
through the SAME invariants the model checker proves on the abstract
protocol (analysis/conformance.py), so chaos runs can assert the live
system conforms.

Fast, no devices, no processes: wired into tier-1 via
tests/test_analysis.py. CI/bench records can attach the --json report.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the schedule analyzer imports jax (through parallel/reshard.py);
# keep the CLI runnable on devices-less hosts
os.environ.setdefault('JAX_PLATFORMS', 'cpu')


def _analyzers():
    from autodist_tpu.analysis import (env_lint, explore, fence_lint,
                                       schedule_lint)
    # cheap lints first; the model checker explores last
    return (('fence', fence_lint.analyze),
            ('env', env_lint.analyze),
            ('schedule', schedule_lint.analyze),
            ('protocol', explore.analyze))


def run(names=None):
    """Run the selected analyzers; returns the report dict."""
    report = {'analyzers': {}, 'clean': True, 'findings': 0}
    for name, fn in _analyzers():
        if names is not None and name not in names:
            continue
        t0 = time.monotonic()
        findings = fn()
        report['analyzers'][name] = {
            'findings': findings,
            'elapsed_s': round(time.monotonic() - t0, 3)}
        report['findings'] += len(findings)
        if findings:
            report['clean'] = False
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='repo-wide static analysis (exit 0 = zero '
                    'findings)')
    ap.add_argument('--all', action='store_true',
                    help='run every analyzer')
    ap.add_argument('--protocol', action='store_true',
                    help='control-plane protocol model checker')
    ap.add_argument('--fence', action='store_true',
                    help='coord_service.cc fence-coverage lint')
    ap.add_argument('--env', action='store_true',
                    help='AUTODIST_* env-knob lint')
    ap.add_argument('--schedule', action='store_true',
                    help='schedule/plan consistency lint')
    ap.add_argument('--json', action='store_true',
                    help='print a machine-readable JSON report')
    ap.add_argument('--conformance', nargs='+', metavar='DUMP',
                    help='replay flight-recorder dump(s) through the '
                         'protocol-model invariants instead of the '
                         'static analyzers')
    args = ap.parse_args(argv)
    if args.conformance:
        from autodist_tpu.analysis import conformance
        findings = conformance.analyze(args.conformance)
        report = {'analyzers': {'conformance': {
            'findings': findings, 'elapsed_s': 0.0}},
            'clean': not findings, 'findings': len(findings)}
        if args.json:
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            for f in findings:
                print('  - ' + f)
            print('conformance %s: %d finding(s)'
                  % ('CLEAN' if not findings else 'FAILED',
                     len(findings)))
        return 0 if not findings else 1
    selected = {n for n in ('protocol', 'fence', 'env', 'schedule')
                if getattr(args, n)}
    if args.all or not selected:
        selected = None
    report = run(selected)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for name, rec in report['analyzers'].items():
            status = 'clean' if not rec['findings'] else \
                '%d finding(s)' % len(rec['findings'])
            print('%-9s %s (%.2fs)' % (name, status, rec['elapsed_s']))
            for f in rec['findings']:
                print('  - ' + f.replace('\n', '\n    '))
        print('analysis %s: %d finding(s)'
              % ('CLEAN' if report['clean'] else 'FAILED',
                 report['findings']))
    return 0 if report['clean'] else 1


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
