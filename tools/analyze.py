"""Repo-wide static analysis CLI — one entry over the seven analyzers.

    python tools/analyze.py --all            # everything, exit 0 = clean
    python tools/analyze.py --fence --env    # just those analyzers
    python tools/analyze.py --all --json     # machine-readable report
    python tools/analyze.py --conformance dump.json   # replay a
             # flight-recorder dump through the protocol invariants

Analyzers (autodist_tpu/analysis/, design notes in
docs/design/static-analysis.md):

  protocol    bounded model checking of the control-plane protocol
              (HEAD orderings explore clean; the seeded historical
              bugs must still re-derive as counterexamples)
  data-plane  bounded model checking of the PS data plane: chunked
              write sequences + torn-read parity, fence-recheck under
              the tensor lock, the depth-2 pipeline's prefetch floor,
              the telemetry batch cursor (seeded: PR 1 offset-0
              abort, PR 5 disconnect wedge, PR 11 cursor race)
  epoch-swap  the strategy-distribution-epoch handshake model
              (ROADMAP 2, implemented in PR 19): the verified
              stage->ack->arm->boundary ordering explores clean, the
              tempting-but-wrong orderings counterexample
  swap-conformance
              epoch-swap trace conformance: the synthetic verified
              trace replays clean, seeded bad traces produce their
              findings, and runtime/swap_keys.py's key schema pins to
              the model's symbol table (spec<->impl drift guard)
  fence       coord_service.cc dispatcher fence-coverage + payload
              bounds + header table drift (absorbs
              tools/check_protocol.py)
  env         AUTODIST_* env reads declared + worker knobs forwarded
              + docs mention every knob (choice sets in sync)
  schedule    schedule-IR shape algebra run ONCE over every
              emitter-reachable dimension combination (with a seeded
              wrong-schedule counterexample as the sensitivity
              guard), a thin routes-through-the-IR drift check on
              both emission paths, program_time/entry_time pricing
              parity, reshard shape algebra (each move verified via
              its own IR program), wire-pricing drift (absorbs
              tools/check_wire_pricing.py)

``--conformance <dump>...`` is the dynamic twin (docs/design/
observability.md): it replays the crash flight recorder's event trace
through the SAME invariants the model checker proves on the abstract
protocol (analysis/conformance.py), so chaos runs can assert the live
system conforms.

Fast, no devices, no processes: wired into tier-1 via
tests/test_analysis.py. CI/bench records attach the --json report
(``bench.py`` stores it under the stable ``analysis`` BENCH key, and
``tools/bench_compare.py`` flags analyzer-cost / state-space blowup
regressions across records). The report carries ``schema_version``
(bumped on shape changes), per-pass wall time, and — for the model
checkers — states-explored counts.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the schedule analyzer imports jax (through parallel/reshard.py);
# keep the CLI runnable on devices-less hosts
os.environ.setdefault('JAX_PLATFORMS', 'cpu')

#: Version of the --json report shape. Bump when a field is renamed,
#: removed, or changes meaning — bench_compare keys off dotted paths
#: into this report, and a silent shape change would read as metrics
#: vanishing rather than as an incompatibility.
SCHEMA_VERSION = 2

ANALYZER_NAMES = ('protocol', 'data-plane', 'epoch-swap',
                  'swap-conformance', 'fence', 'env', 'schedule')


def _analyzers():
    from autodist_tpu.analysis import (data_plane_model, env_lint,
                                       epoch_swap_model, explore,
                                       fence_lint, schedule_lint,
                                       swap_conformance)
    # cheap lints first; the model checkers explore last
    return (('fence', fence_lint, fence_lint.analyze),
            ('env', env_lint, env_lint.analyze),
            ('swap-conformance', swap_conformance,
             swap_conformance.analyze),
            ('schedule', schedule_lint, schedule_lint.analyze),
            ('protocol', explore, explore.analyze),
            ('data-plane', data_plane_model, data_plane_model.analyze),
            ('epoch-swap', epoch_swap_model, epoch_swap_model.analyze))


def run(names=None):
    """Run the selected analyzers; returns the report dict."""
    report = {'schema_version': SCHEMA_VERSION, 'analyzers': {},
              'clean': True, 'findings': 0}
    for name, mod, fn in _analyzers():
        if names is not None and name not in names:
            continue
        t0 = time.monotonic()
        findings = fn()
        rec = {'findings': findings,
               'elapsed_s': round(time.monotonic() - t0, 3)}
        # model-checker passes publish their exploration size; the
        # lints have none (getattr: LAST_STATS is a checker contract)
        stats = getattr(mod, 'LAST_STATS', None)
        if stats and 'states_explored' in stats:
            rec['states_explored'] = stats['states_explored']
            rec['scenarios'] = dict(stats['scenarios'])
        report['analyzers'][name] = rec
        report['findings'] += len(findings)
        if findings:
            report['clean'] = False
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='repo-wide static analysis (exit 0 = zero '
                    'findings)')
    ap.add_argument('--all', action='store_true',
                    help='run every analyzer')
    ap.add_argument('--protocol', action='store_true',
                    help='control-plane protocol model checker')
    ap.add_argument('--data-plane', action='store_true',
                    dest='data_plane',
                    help='PS data-plane model checker (chunk '
                         'sequences, torn reads, pipeline floors, '
                         'telemetry cursor)')
    ap.add_argument('--epoch-swap', action='store_true',
                    dest='epoch_swap',
                    help='strategy-distribution-epoch handshake model '
                         '(the ROADMAP 2 contract)')
    ap.add_argument('--swap-conformance', action='store_true',
                    dest='swap_conformance',
                    help='epoch-swap trace conformance: synthetic '
                         'verified/seeded traces + key-schema pin '
                         'against the model symbol table')
    ap.add_argument('--fence', action='store_true',
                    help='coord_service.cc fence-coverage + '
                         'payload-bound lint')
    ap.add_argument('--env', action='store_true',
                    help='AUTODIST_* env-knob lint (declaration, '
                         'forwarding, docs drift)')
    ap.add_argument('--schedule', action='store_true',
                    help='schedule-IR shape-algebra verification + '
                         'routes-through-IR drift lint')
    ap.add_argument('--json', action='store_true',
                    help='print a machine-readable JSON report')
    ap.add_argument('--conformance', nargs='+', metavar='DUMP',
                    help='replay flight-recorder dump(s) through the '
                         'protocol-model invariants instead of the '
                         'static analyzers')
    args = ap.parse_args(argv)
    if args.conformance:
        from autodist_tpu.analysis import conformance
        findings = conformance.analyze(args.conformance)
        report = {'schema_version': SCHEMA_VERSION,
                  'analyzers': {'conformance': {
                      'findings': findings, 'elapsed_s': 0.0}},
                  'clean': not findings, 'findings': len(findings)}
        if args.json:
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            for f in findings:
                print('  - ' + f)
            print('conformance %s: %d finding(s)'
                  % ('CLEAN' if not findings else 'FAILED',
                     len(findings)))
        return 0 if not findings else 1
    selected = {n for n in ANALYZER_NAMES
                if getattr(args, n.replace('-', '_'))}
    if args.all or not selected:
        selected = None
    report = run(selected)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for name, rec in report['analyzers'].items():
            status = 'clean' if not rec['findings'] else \
                '%d finding(s)' % len(rec['findings'])
            states = ', %d states' % rec['states_explored'] \
                if 'states_explored' in rec else ''
            print('%-11s %s (%.2fs%s)' % (name, status,
                                          rec['elapsed_s'], states))
            for f in rec['findings']:
                print('  - ' + f.replace('\n', '\n    '))
        print('analysis %s: %d finding(s)'
              % ('CLEAN' if report['clean'] else 'FAILED',
                 report['findings']))
    return 0 if report['clean'] else 1


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
