"""Measure the pipeline-schedule trade table (VERDICT r4 item 3).

For pp in {2, 4}: GPipe vs legacy-1F1B vs fused-1F1B(remat) vs
fused-1F1B(stash), all through the same Trainer/TransformerLM path on
the 8-device virtual CPU mesh. Reported per config:

- compiled FLOPs (``compiled.cost_analysis()['flops']``) — recorded
  but NOT comparable across these four programs (while-loop bodies
  count once and the schedules have different loop structures — see
  the BASELINE.md round-5 caveats),
- temp memory (``memory_analysis().temp_size_in_bytes``) — the
  activation working set,
- wall step time on the CPU mesh (1 host core, so wall ≈ serialized
  total compute) — the compute evidence, with that caveat stated.

Run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/pp_schedule_table.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from autodist_tpu.utils.jax_env import apply_jax_env_overrides

apply_jax_env_overrides()

import dataclasses

import numpy as np

import jax
import optax

from autodist_tpu.api import Trainer
from autodist_tpu.models.transformer import (TransformerConfig,
                                             TransformerLM)
from autodist_tpu.parallel.axes import ParallelSpec


def measure(model, batch, pp, schedule, variant, microbatches, steps=3):
    tr = Trainer(model, optax.sgd(0.1),
                 spec=ParallelSpec(pp=pp, dp=1,
                                   microbatches=microbatches,
                                   pp_schedule=schedule,
                                   pp_variant=variant))
    state = tr.init(jax.random.PRNGKey(0))
    compiled = tr.compile_step(state, batch)
    mem = compiled.memory_analysis().temp_size_in_bytes
    cost = compiled.cost_analysis()
    flops = cost.get('flops', float('nan')) if cost else float('nan')
    sharded = tr.shard_batch(batch)
    state, m = compiled(state, sharded)   # warmup
    loss = float(m['loss'])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = compiled(state, sharded)
    float(m['loss'])
    dt = (time.perf_counter() - t0) / steps
    return {'temp_mb': mem / 1e6, 'gflops': flops / 1e9,
            'step_s': dt, 'loss': loss}


def main():
    cfg = dataclasses.replace(
        TransformerConfig.tiny(dtype=np.float32, n_layers=8,
                               max_len=128), vocab=4096)
    model = TransformerLM(cfg)
    rng = np.random.RandomState(0)
    batch = {'tokens': rng.randint(0, 4096, (32, 128)),
             'targets': rng.randint(0, 4096, (32, 128))}
    M = 16
    rows = []
    for pp in (2, 4):
        for label, schedule, variant in (
                ('gpipe', 'gpipe', 'auto'),
                ('legacy-1f1b', '1f1b', 'legacy'),
                ('fused-remat', '1f1b', 'remat'),
                ('fused-stash', '1f1b', 'stash')):
            r = measure(model, batch, pp, schedule, variant, M)
            r.update(pp=pp, config=label)
            rows.append(r)
            print(json.dumps(r), flush=True)
    # quick consistency: every config trains the same loss
    losses = {round(r['loss'], 3) for r in rows}
    print('# distinct warmup losses (expect 1):', losses)


if __name__ == '__main__':
    main()
