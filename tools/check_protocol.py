"""Coord-service protocol drift check.

Asserts that the command list documented in ``coord_service.cc``'s
header comment matches the dispatcher's actual ``cmd == "..."`` set.
The two have drifted before (BSTAT shipped undocumented), and the
header is what operators and the client read — a drifted header is a
protocol doc bug.

Run:  python tools/check_protocol.py      (exit 0 = in sync)
Wired into tier-1 via tests/test_sparse_ps.py.
"""
import os
import re
import sys

SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   'autodist_tpu', 'native', 'coord_service.cc')

#: AUTH is consumed by the connection handshake (serve_conn) before any
#: command reaches handle(); it belongs in the header but can never
#: appear in the dispatcher.
HANDSHAKE_ONLY = {'AUTH'}


def documented_commands(text):
    """Commands listed in the header comment's protocol table: lines of
    the form ``//   CMD <args...> -> reply`` before the first
    ``#include`` (continuation lines are indented further and reply
    tokens never start a line)."""
    header = text.split('#include', 1)[0]
    return set(re.findall(r'^//   ([A-Z][A-Z0-9]*)\b', header, re.M))


def dispatched_commands(text):
    """Commands the dispatcher actually matches (``cmd == "..."``)."""
    return set(re.findall(r'cmd == "([A-Z][A-Z0-9]*)"', text))


def find_drift(text=None):
    """Returns a list of human-readable drift problems (empty = in
    sync)."""
    if text is None:
        with open(SRC) as f:
            text = f.read()
    doc = documented_commands(text)
    disp = dispatched_commands(text)
    problems = []
    for cmd in sorted(disp - doc):
        problems.append('dispatched but not documented in the header '
                        'comment: %s' % cmd)
    for cmd in sorted(doc - disp - HANDSHAKE_ONLY):
        problems.append('documented in the header comment but not '
                        'dispatched: %s' % cmd)
    if not doc:
        problems.append('no documented commands found — the header '
                        'comment table moved or changed format')
    return problems


def main(argv=None):
    problems = find_drift()
    if problems:
        print('coord_service.cc protocol drift:')
        for p in problems:
            print('  - ' + p)
        return 1
    print('coord_service.cc header comment and dispatcher agree (%d '
          'commands)' % len(dispatched_commands(open(SRC).read())))
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
