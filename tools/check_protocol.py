"""Coord-service protocol drift check — compatibility shim.

The check lives in :mod:`autodist_tpu.analysis.fence_lint` now (PR 9
folded it into the static-analysis subsystem, generalized to full
fence-coverage linting); this entry point keeps the documented
``python tools/check_protocol.py`` invocation working and re-exports
the original API (``SRC``, ``find_drift``, ``documented_commands``,
``dispatched_commands``). Prefer ``python tools/analyze.py --fence``,
which also verifies every mutating command is fence-checked.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from autodist_tpu.analysis.fence_lint import (  # noqa: F401,E402
    HANDSHAKE_ONLY, SRC, dispatched_commands, documented_commands,
    find_drift)


def main(argv=None):
    problems = find_drift()
    if problems:
        print('coord_service.cc protocol drift:')
        for p in problems:
            print('  - ' + p)
        return 1
    print('coord_service.cc header comment and dispatcher agree (%d '
          'commands)' % len(dispatched_commands(open(SRC).read())))
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
