"""A/B the space-to-depth stem transform on the CNN family (TPU).

VERDICT r4 item 1: measure AUTODIST_S2D_STEM=0 vs 1 train steps for
ResNet-101 / DenseNet-121 / InceptionV3 at their bench batch sizes.
Uses bench.run_workload (median of 3 fenced blocks).
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import bench as B


def run(name, steps=10):
    import jax.numpy as jnp
    import optax

    from autodist_tpu.models import vision

    builders = {
        'resnet101': (lambda: vision.ResNet.resnet101(dtype=jnp.bfloat16),
                      256, 224),
        'densenet121': (lambda: vision.DenseNet.densenet121(
            dtype=jnp.bfloat16), 128, 224),
        'inceptionv3': (lambda: vision.InceptionV3(dtype=jnp.bfloat16),
                        128, 299),
    }
    fn, batch_size, hw = builders[name]
    rng = np.random.RandomState(0)
    batch = {'images': rng.rand(batch_size, hw, hw, 3).astype('f4'),
             'labels': rng.randint(0, 10, (batch_size,), dtype=np.int32)}
    out = {}
    for flag in ('0', '1'):
        os.environ['AUTODIST_S2D_STEM'] = flag
        stats = {}
        dt, _ = B.run_workload(fn(), batch, steps,
                               optimizer=optax.sgd(0.1, momentum=0.9),
                               stats_out=stats)
        out['s2d_%s' % flag] = {
            'step_ms': round(1000 * dt / steps, 2),
            'img_per_s': round(batch_size * steps / dt, 1),
            'dispersion_pct': stats['dispersion_pct']}
    return out


def main():
    from autodist_tpu.utils.jax_env import apply_jax_env_overrides
    apply_jax_env_overrides()
    names = sys.argv[1:] or ['resnet101', 'densenet121', 'inceptionv3']
    for name in names:
        print(name, json.dumps(run(name)), flush=True)


if __name__ == '__main__':
    main()
