"""Diff two BENCH records per stable key — the machine-readable half
of the bench trajectory.

    python tools/bench_compare.py BENCH_r05.json BENCH_r06.json
    python tools/bench_compare.py OLD.json NEW.json --threshold 0.15
    python tools/bench_compare.py OLD.json NEW.json --json

Each BENCH_r*.json is either the driver wrapper (``{'parsed': {...}}``)
or bench.py's raw output line. The comparison walks a curated metric
table grouped by the stable record keys (grad_sync, quantized,
hierarchical, weight_update, elastic, ps_pipeline, local_sgd,
telemetry, monitor, analysis, roofline, top-level throughput) with a
per-metric
direction; a NEW value worse
than OLD by
more than ``--threshold`` (fractional, default 0.10) is a REGRESSION.
Metrics missing from either record are reported as skipped, never
fatal — older records predate newer keys.

Cross-platform comparisons are REFUSED (exit 2): records carry
``extra.platform``, and a CPU-smoke number regressing against a TPU
number is noise wearing a trend costume. ``--allow-cross-platform``
overrides for exploratory use.

Exit codes: 0 = no regression, 1 = regression(s), 2 = unusable input /
platform refusal.
"""
import argparse
import json
import sys

#: (stable key, dotted path, direction, label). Direction 'lower' =
#: smaller is better (times, overhead), 'higher' = bigger is better
#: (throughput, reduction ratios, overlap).
METRICS = (
    ('top', 'value', 'higher', 'headline throughput'),
    ('grad_sync', 'extra.grad_sync.per_step_sync_time_s', 'lower',
     'per-step grad sync time'),
    ('grad_sync', 'extra.grad_sync.sync_wire_bytes', 'lower',
     'grad sync wire bytes'),
    ('quantized', 'extra.quantized.grad_sync.bytes_reduction', 'higher',
     'int8 grad-sync wire reduction'),
    ('quantized', 'extra.quantized.ps_push.push_bytes_reduction',
     'higher', 'int8 PS push-byte reduction'),
    ('hierarchical', 'extra.hierarchical.dcn_bytes_reduction', 'higher',
     'two-level DCN byte reduction'),
    ('weight_update', 'extra.weight_update.opt_slot_bytes_reduction',
     'higher', 'weight-update opt-slot memory reduction'),
    ('weight_update', 'extra.weight_update.sharded.per_step_wall_s',
     'lower', 'sharded-update per-step wall'),
    ('weight_update',
     'extra.weight_update.sharded.all_gather_wire_bytes', 'lower',
     'weight-update param all-gather wire bytes'),
    ('elastic', 'extra.elastic.admit_wall_s', 'lower',
     'elastic admit wall time'),
    ('elastic', 'extra.elastic.steps_blocked', 'lower',
     'steps blocked by the join'),
    # the epoch-swap trajectory (PR 19): bytes_resharded is
    # deterministic byte accounting of the re-key; downtime and
    # steps-to-boundary are handshake-latency counters over one-shot
    # thread-timed runs, so they carry the wide 5x scale. A
    # state_max_abs_diff of -1 is the failure sentinel (the migration
    # never landed); otherwise the moved-not-recomputed claim makes it
    # exactly 0.0 and the zero-baseline epsilon catches the first
    # divergent bit.
    ('epoch_swap', 'extra.epoch_swap.bytes_resharded', 'lower',
     'epoch-swap re-key wire bytes'),
    ('epoch_swap', 'extra.epoch_swap.swap_downtime_steps', 'lower',
     'steps stalled by the epoch swap', 5),
    ('epoch_swap', 'extra.epoch_swap.steps_to_boundary', 'lower',
     'epoch-swap request-to-boundary steps', 5),
    ('epoch_swap', 'extra.epoch_swap.state_max_abs_diff', 'lower',
     'epoch-swap final-state divergence vs control (-1 = no swap)'),
    ('ps_pipeline', 'extra.ps_pipeline.depth2.overlap_frac', 'higher',
     'PS pipeline depth-2 overlap fraction'),
    ('ps_pipeline', 'extra.ps_pipeline.depth2_speedup', 'higher',
     'PS pipeline depth-2 speedup'),
    # the local-SGD window trajectory (ISSUE 16): the wire-bytes
    # ratio is deterministic byte accounting (~H by construction, so
    # it gates at the normal threshold); the per-step walls are
    # injected-delay-dominated one-shot timings and the divergence is
    # float noise around 0 — both carry the wide 5x scale. A
    # divergence of -1 would be the failure sentinel (legs did not
    # both finish); the sentinel rule below handles it.
    ('local_sgd', 'extra.local_sgd.wire_bytes_ratio', 'higher',
     'local-SGD H=8 wire-bytes reduction'),
    ('local_sgd', 'extra.local_sgd.wall_speedup', 'higher',
     'local-SGD H=8 weak-link wall speedup', 5),
    ('local_sgd', 'extra.local_sgd.h8.per_step_wall_s', 'lower',
     'local-SGD H=8 per-step wall', 5),
    ('local_sgd', 'extra.local_sgd.divergence', 'lower',
     'local-SGD H=8 final-state divergence', 5),
    # the train-while-serve trajectory (ISSUE 17): the slowdown ratio
    # and lookup latencies are one-shot concurrent-thread timings
    # (scheduler-noise dominated), so they carry the wide 5x scale.
    # The three consistency gates are deterministic: staleness_guard
    # is +1/-1 (-1 = a replica accepted a snapshot past its staleness
    # bound — the failure-sentinel rule fires), mixed_version_reads
    # counts torn snapshots (must stay 0; the zero-baseline epsilon
    # catches the first one appearing), and snapshot_divergence is
    # bit-exactness of the final pinned snapshot on the f32 wire.
    ('serving', 'extra.serving.trainer_slowdown', 'lower',
     'train-while-serve trainer slowdown ratio', 5),
    ('serving', 'extra.serving.serving.lookup_p99_ms', 'lower',
     'serving lookup p99 latency', 5),
    ('serving', 'extra.serving.serving.qps', 'higher',
     'serving fleet lookup throughput', 5),
    ('serving', 'extra.serving.staleness_guard', 'higher',
     'serving staleness-bound guard (-1 = bound violated)'),
    ('serving', 'extra.serving.mixed_version_reads', 'lower',
     'serving torn-snapshot reads'),
    ('serving', 'extra.serving.snapshot_divergence', 'lower',
     'serving final-snapshot divergence vs authoritative read'),
    ('telemetry', 'extra.telemetry.overhead_frac', 'lower',
     'telemetry overhead fraction'),
    ('monitor', 'extra.monitor.detection_steps', 'lower',
     'straggler detection latency (steps)'),
    ('monitor', 'extra.monitor.clean.false_positive_verdicts', 'lower',
     'clean-leg false positives'),
    ('monitor', 'extra.monitor.overhead_frac', 'lower',
     'monitor poll overhead fraction'),
    # the static-analysis trajectory: analyzer wall cost and model-
    # checker state-space size are both tier-1 budget items — a pass
    # that quietly doubles its exploration is a regression even at
    # zero findings. The wall times are SINGLE-SHOT subprocess
    # measurements (interpreter + import dominated), so they carry a
    # 5x threshold scale: a real blowup roughly doubles them, machine
    # noise does not move them 50%. The deterministic states counts
    # gate at the normal threshold.
    ('analysis', 'extra.analysis.total_elapsed_s', 'lower',
     'static-analysis total wall time', 5),
    ('analysis', 'extra.analysis.states_explored_total', 'lower',
     'model-checker states explored (all passes)'),
    ('analysis', 'extra.analysis.passes.protocol.elapsed_s', 'lower',
     'protocol model-checker wall time', 5),
    ('analysis', 'extra.analysis.passes.data-plane.states_explored',
     'lower', 'data-plane model states explored'),
    ('analysis', 'extra.analysis.passes.epoch-swap.states_explored',
     'lower', 'epoch-swap model states explored'),
    # the device-plane roofline trajectory (ISSUE 15): MFU is the
    # headline (json-null on the CPU fallback -> skipped; -1 = the
    # measurement itself failed = failure sentinel, regression);
    # per-tier achieved bandwidth and the drift ratios gate the cost
    # model's honesty. The microbench-sourced numbers are noisy
    # single-host timings, so the drift ratios carry a wide scale.
    ('roofline', 'extra.roofline.mfu', 'higher', 'per-step MFU'),
    ('roofline', 'extra.roofline.drift.tiers.ici.achieved_bytes_per_s',
     'higher', 'ICI achieved bytes/s (per-entry join)', 5),
    ('roofline', 'extra.roofline.drift.tiers.dcn.achieved_bytes_per_s',
     'higher', 'DCN achieved bytes/s (per-entry join)', 5),
    ('roofline', 'extra.roofline.memory.abs_drift', 'lower',
     'HBM estimate drift |ratio-1|', 5),
    ('roofline', 'extra.roofline.drift.worst_drift_ratio', 'lower',
     'worst per-entry collective drift', 5),
    # the collective-schedule-IR trajectory (ISSUE 20): the predicted
    # speedup, per-tier bytes, and verification wall are deterministic
    # cost-model/shape-algebra outputs (normal threshold; the verify
    # wall is sub-millisecond interpreter work, so it rides the wide
    # scale anyway); the measured per-step syncs are CPU-mesh
    # collective timings (5x scale). state_max_abs_diff is the
    # synth-vs-hand synced-state divergence — seeded grads make the
    # wire-quantization error deterministic, and -1 is the failure
    # sentinel (a leg never produced a synced state).
    ('schedule_ir', 'extra.schedule_ir.predicted_speedup', 'higher',
     'synthesized-vs-hand-written predicted schedule speedup'),
    ('schedule_ir', 'extra.schedule_ir.synthesized.tier_bytes.dcn',
     'lower', 'synthesized-best DCN bytes per step'),
    ('schedule_ir', 'extra.schedule_ir.verify_total_s', 'lower',
     'schedule-IR verification wall (all candidates)', 5),
    ('schedule_ir',
     'extra.schedule_ir.handwritten.measured_per_step_s', 'lower',
     'hand-written-best measured per-step sync', 5),
    ('schedule_ir',
     'extra.schedule_ir.synthesized.measured_per_step_s', 'lower',
     'synthesized-best measured per-step sync', 5),
    ('schedule_ir', 'extra.schedule_ir.state_max_abs_diff', 'lower',
     'synth-vs-hand synced-state divergence (-1 = leg failed)'),
)


def load_record(path):
    """A BENCH file -> the bench.py result dict (unwrapping the
    driver's ``{'parsed': ...}`` envelope). Raises ValueError when
    neither shape fits."""
    with open(path) as f:
        payload = json.load(f)
    if isinstance(payload, dict) and isinstance(
            payload.get('parsed'), dict):
        payload = payload['parsed']
    if not isinstance(payload, dict) or 'metric' not in payload:
        raise ValueError(
            '%s: not a BENCH record (no parsed bench result with a '
            "'metric' field — rc!=0 runs carry parsed=null)" % path)
    return payload


def _lookup(record, path):
    cur = record
    for part in path.split('.'):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur if isinstance(cur, (int, float)) and not isinstance(
        cur, bool) else None


def compare(old, new, threshold=0.10):
    """Walk the metric table; returns the report dict."""
    rows = []
    regressions = 0
    for entry in METRICS:
        key, path, direction, label = entry[:4]
        # optional 5th element: per-metric threshold scale (noisy
        # one-shot wall times gate wider than deterministic counts)
        scale = entry[4] if len(entry) > 4 else 1
        a, b = _lookup(old, path), _lookup(new, path)
        row = {'key': key, 'metric': path, 'label': label,
               'direction': direction, 'old': a, 'new': b}
        if a is None or b is None:
            row['status'] = 'skipped'
            row['note'] = 'missing in %s record' % (
                'both' if a is None and b is None
                else ('old' if a is None else 'new'))
        elif a < 0 or b < 0:
            # a negative value is a FAILURE SENTINEL in BOTH
            # directions (PR 11 rule, extended for the roofline
            # metrics' null/-1 convention): lower-is-better, -1 would
            # read as the best possible value (detection_steps=-1 =
            # never detected); higher-is-better, -1 marks "the
            # measurement itself failed" distinct from json-null
            # ("legitimately unavailable", which skips above)
            if b < 0:
                row['status'] = 'regression'
                row['note'] = ('failure sentinel in new record '
                               '(%g): the measurement itself failed'
                               % b)
                regressions += 1
            else:
                row['status'] = 'ok'
                row['note'] = ('old record carries a failure '
                               'sentinel (%g); any measured new '
                               'value is an improvement' % a)
        else:
            if direction == 'lower':
                # worse = bigger; ratio vs the old value, with an
                # absolute epsilon so 0 -> 0.0001 (a count appearing)
                # still registers against a zero baseline
                worse = (b - a) / a if a else (1.0 if b > 1e-12 else 0.0)
            else:
                worse = (a - b) / a if a else 0.0
            row['delta_frac'] = round(worse, 4)
            row['status'] = ('regression'
                             if worse > threshold * scale else 'ok')
            if row['status'] == 'regression':
                regressions += 1
        rows.append(row)
    return {'threshold': threshold, 'rows': rows,
            'regressions': regressions, 'clean': regressions == 0}


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='diff two BENCH records per stable key; nonzero '
                    'exit on regression')
    ap.add_argument('old')
    ap.add_argument('new')
    ap.add_argument('--threshold', type=float, default=0.10,
                    help='fractional regression threshold (default '
                         '0.10 = 10%%)')
    ap.add_argument('--allow-cross-platform', action='store_true',
                    help='compare records from different platforms '
                         'anyway (normally refused)')
    ap.add_argument('--json', action='store_true',
                    help='print the machine-readable report')
    args = ap.parse_args(argv)
    try:
        old = load_record(args.old)
        new = load_record(args.new)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print('bench_compare: %s' % e, file=sys.stderr)
        return 2
    p_old = (old.get('extra') or {}).get('platform')
    p_new = (new.get('extra') or {}).get('platform')
    if p_old and p_new and p_old != p_new and \
            not args.allow_cross_platform:
        print('bench_compare: REFUSED — %s is a %r record, %s is %r; '
              'cross-platform deltas are noise, not a trend '
              '(--allow-cross-platform to override)'
              % (args.old, p_old, args.new, p_new), file=sys.stderr)
        return 2
    report = compare(old, new, threshold=args.threshold)
    report['platform'] = p_new or p_old
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for row in report['rows']:
            if row['status'] == 'skipped':
                print('  skip  %-38s (%s)' % (row['label'], row['note']))
                continue
            mark = {'ok': '  ok  ', 'regression': 'REGR  '}[row['status']]
            if 'delta_frac' not in row:   # failure-sentinel rows
                print('%s%-38s %12.6g -> %-12.6g (%s)'
                      % (mark, row['label'], row['old'], row['new'],
                         row['note']))
                continue
            print('%s%-38s %12.6g -> %-12.6g (%+.1f%% worse, %s '
                  'better)' % (mark, row['label'], row['old'],
                               row['new'], 100 * row['delta_frac'],
                               row['direction']))
        print('bench_compare %s: %d regression(s) at threshold %.0f%%'
              % ('CLEAN' if report['clean'] else 'FAILED',
                 report['regressions'], 100 * args.threshold))
    return 0 if report['clean'] else 1


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
