"""Rank sync/partition strategies for a model + resource spec — offline.

Prints the simulator's ranked table (predicted step time, per-device
peak bytes, collective count per candidate builder) WITHOUT running a
single training step: only ``jax.eval_shape`` touches the model, so
this works on a TPU-less host.

Runs under the CPU fallback::

    JAX_PLATFORMS=cpu python tools/simulate.py --model ncf
    python tools/simulate.py --model lstm --resource-spec cluster.yml \
        --budget-gb 8 --json

Without ``--resource-spec`` a single-node spec is synthesized from
``--devices`` / ``--device-type`` (topology hints then come from the
per-type defaults; pass a YAML spec with a ``topology:`` block to price
a real mesh).
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU fallback BEFORE any jax import: 8 virtual devices (jax_env is
# jax-import-free at module level, so this is safe to import first)
from autodist_tpu.utils.jax_env import (  # noqa: E402
    apply_jax_env_overrides, force_cpu_host_devices)

force_cpu_host_devices(8)
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
apply_jax_env_overrides()


def build_model(name):
    """Model registry for the bench model set (shapes only — no steps).

    Returns (model, optimizer_slots).
    """
    import jax.numpy as jnp
    if name == 'ncf':
        from autodist_tpu.models.ncf import NCF
        return NCF(138493, 26744, mf_dim=64, mlp_dims=(256, 128, 64)), 2
    if name == 'lstm':
        from autodist_tpu.models.rnn import LSTMLM
        return LSTMLM(vocab=100000, dim=512, hidden=1024, n_layers=2), 2
    if name == 'tinylm':
        from autodist_tpu.models.transformer import (TransformerConfig,
                                                     TransformerLM)
        return TransformerLM(TransformerConfig.tiny(
            dtype=jnp.float32)), 2
    if name == 'resnet':
        from autodist_tpu.models.vision import ResNet
        return ResNet((1, 1), num_classes=10, dtype=jnp.float32), 1
    raise SystemExit('unknown --model %r (ncf, lstm, tinylm, resnet)'
                     % name)


def build_resource_spec(args):
    from autodist_tpu.resource_spec import ResourceSpec
    if args.resource_spec:
        return ResourceSpec(resource_file=args.resource_spec)
    n_nodes = max(1, args.nodes)
    if args.devices % n_nodes:
        raise SystemExit('--nodes %d must divide --devices %d'
                         % (n_nodes, args.devices))
    per = args.devices // n_nodes
    key = {'tpu': 'tpus', 'gpu': 'gpus', 'cpu': 'cpus'}[args.device_type]
    nodes = []
    for i in range(n_nodes):
        node = {'address': 'host%d' % i if n_nodes > 1 else 'localhost',
                'cpus': [0], 'network_bandwidth': 100}
        if i == 0:
            node['chief'] = True
        if args.device_type == 'cpu':
            node['cpus'] = list(range(per))
        else:
            node[key] = list(range(per))
        nodes.append(node)
    return ResourceSpec(resource_info={'nodes': nodes})


def main(argv=None):
    p = argparse.ArgumentParser(
        description='Simulate strategy candidates (no training runs).')
    p.add_argument('--model', default='tinylm',
                   help='ncf | lstm | tinylm | resnet')
    p.add_argument('--resource-spec', default='',
                   help='YAML resource spec (else synthesized)')
    p.add_argument('--devices', type=int, default=8,
                   help='device count for the synthesized spec')
    p.add_argument('--device-type', default='tpu',
                   choices=('tpu', 'gpu', 'cpu'),
                   help='device type for the synthesized spec')
    p.add_argument('--replicas', type=int, default=0,
                   help='override the replica count priced (default: '
                        'the spec accelerator count)')
    p.add_argument('--budget-gb', type=float, default=0,
                   help='per-device memory budget; 0 = no pruning')
    p.add_argument('--optimizer-slots', type=int, default=None,
                   help='f32 slots per param (default per model: '
                        '2 Adam-like, 1 momentum)')
    p.add_argument('--calibrate-trace', default='',
                   help='profiler trace dir to refine alpha-beta from')
    p.add_argument('--ps-overlap', type=float, default=0.0,
                   help='async-PS pull-ahead haircut in [0, 1): the '
                        'fraction of PS param-phase wire time the '
                        'pipelined data plane '
                        '(AUTODIST_PS_PIPELINE_DEPTH>=2) hides; take it '
                        'from a measured ps_stats overlap_frac. 0 '
                        '(default) prices the serial depth-1 plane')
    p.add_argument('--sparse-lookups', type=int, default=4096,
                   help='expected embedding rows one replica looks up '
                        'per step (batch-derived); sparse variables\' '
                        'PS traffic is priced by touched rows, not '
                        'full table size')
    p.add_argument('--nodes', type=int, default=1,
                   help='synthesize this many nodes (devices split '
                        'evenly); >= 2 makes the spec multi-node so '
                        'DCN pricing and hierarchical schedules engage')
    p.add_argument('--hierarchical', action='store_true',
                   help='print BOTH rankings: hierarchical-aware '
                        '(two-level schedules where the cost model '
                        'picks them) and flat-forced — the per-'
                        'topology A/B the schedules are chosen by')
    p.add_argument('--local-steps', default='auto',
                   help='local-SGD window length for the PS(H=...) '
                        'candidates: "auto" (default) enumerates '
                        'H in {2, 4, 8, 16} next to the H=1 PS '
                        'control; an explicit integer restricts the '
                        'enumeration to that one window (1 = H=1 '
                        'only, i.e. no PS(H=...) rows)')
    p.add_argument('--serve-replicas', type=int, default=0,
                   help='price a read-only serving fleet of this many '
                        'replicas next to the ranking (0 = off): each '
                        'replica pulls the dense model over DCN at '
                        '--serve-poll-hz and row-cache misses fetch '
                        'embedding rows on demand')
    p.add_argument('--serve-poll-hz', type=float, default=2.0,
                   help='snapshot poll cadence per replica (the '
                        '1/AUTODIST_SERVE_POLL_S upper bound; only '
                        'accepted polls move tensor bytes)')
    p.add_argument('--serve-qps', type=float, default=0.0,
                   help='fleet-aggregate lookup queries per second')
    p.add_argument('--serve-rows-per-query', type=int, default=256,
                   help='embedding rows touched per lookup query')
    p.add_argument('--serve-row-bytes', type=int, default=256,
                   help='bytes per embedding row (f32 cols x 4)')
    p.add_argument('--serve-row-cache-hit', type=float, default=0.8,
                   help='expected row-cache hit rate in [0, 1] '
                        '(AUTODIST_SERVE_ROW_CACHE_ROWS / '
                        'AUTODIST_SERVE_ROW_TTL_S sizing)')
    p.add_argument('--serve-wire', default='f32',
                   choices=('f32', 'bf16', 'i8'),
                   help='wire dtype of the bulk snapshot pull '
                        '(AUTODIST_SERVE_WIRE)')
    p.add_argument('--schedule-dump', action='store_true',
                   dest='schedule_dump',
                   help='rank schedule-IR candidates (hand-written + '
                        'synthesized) for one gradient bucket over '
                        '--schedule-topo and print each program with '
                        'per-step predicted times and per-tier byte '
                        'totals — the WHY behind the winning schedule')
    p.add_argument('--schedule-topo', default='',
                   dest='schedule_topo',
                   help='topology for --schedule-dump as per-host '
                        'device counts, slices separated by "/" '
                        '(e.g. "4,4/4,2" = 2 slices, the second with '
                        'a 2-device straggler host). Default: one '
                        'slice shaped like the resource spec')
    p.add_argument('--schedule-bytes', type=int, default=0,
                   dest='schedule_bytes',
                   help='bucket size for --schedule-dump (default: '
                        'the model\'s total dense gradient bytes)')
    p.add_argument('--json', action='store_true',
                   help='emit one JSON object instead of the table')
    args = p.parse_args(argv)

    from autodist_tpu.simulator import search
    from autodist_tpu.simulator.calibrate import calibrate_from_trace
    from autodist_tpu.simulator.cost_model import CostModelParams
    from autodist_tpu.strategy.adapter import PytreeGraphItem

    model, default_slots = build_model(args.model)
    slots = args.optimizer_slots if args.optimizer_slots is not None \
        else default_slots
    rs = build_resource_spec(args)
    gi = PytreeGraphItem(model)
    params = CostModelParams.from_topology(rs.topology)
    if not 0.0 <= args.ps_overlap < 1.0:
        raise SystemExit('--ps-overlap must be in [0, 1); got %r'
                         % args.ps_overlap)
    params.ps_overlap_discount = args.ps_overlap
    n = args.replicas or None
    if args.calibrate_trace:
        from autodist_tpu.strategy.builders import replica_devices
        params = calibrate_from_trace(
            params, args.calibrate_trace,
            n or len(replica_devices(rs)),
            cross_node=rs.topology.multi_node)
    budget = int(args.budget_gb * (1 << 30)) if args.budget_gb else None
    if args.local_steps == 'auto':
        local_hs = (2, 4, 8, 16)
    else:
        try:
            h = int(args.local_steps)
        except ValueError:
            raise SystemExit('--local-steps must be "auto" or an '
                             'integer >= 1; got %r' % args.local_steps)
        if h < 1:
            raise SystemExit('--local-steps must be >= 1; got %d' % h)
        # 1 = just the H=1 PS control, no PS(H=...) rows
        local_hs = () if h == 1 else (h,)
    candidates = search.default_candidates(local_steps=local_hs)
    feasible, infeasible = search.rank(
        gi, rs, candidates=candidates, memory_budget_bytes=budget,
        params=params, num_replicas=n, optimizer_slots=slots,
        sparse_lookups_per_replica=args.sparse_lookups)
    flat = None
    if args.hierarchical:
        # the flat-forced control ranking: nodes=1 prices every bucket
        # as a flat ring regardless of the spec's node structure
        flat = search.rank(
            gi, rs, candidates=candidates, memory_budget_bytes=budget,
            params=params, num_replicas=n, optimizer_slots=slots,
            sparse_lookups_per_replica=args.sparse_lookups, nodes=1)

    serving = None
    if args.serve_replicas > 0:
        from autodist_tpu.simulator.cost_model import serve_wire_cost
        import numpy as np
        dense_bytes = sum(
            int(np.prod(v.shape or (1,)))
            * np.dtype(v.dtype).itemsize
            for v in gi.trainable_var_op_to_var.values())
        wire_comp = {'f32': None, 'bf16': 'HorovodCompressor',
                     'i8': 'Int8RingCompressor'}[args.serve_wire]
        serving = serve_wire_cost(
            dense_bytes, params=params, replicas=args.serve_replicas,
            poll_hz=args.serve_poll_hz, qps=args.serve_qps,
            rows_per_query=args.serve_rows_per_query,
            row_bytes=args.serve_row_bytes,
            row_cache_hit_rate=args.serve_row_cache_hit,
            compressor=wire_comp)
        serving['wire'] = args.serve_wire

    schedules = None
    if args.schedule_dump:
        import numpy as np
        if args.schedule_topo:
            try:
                slices = tuple(
                    tuple(int(g) for g in s.split(','))
                    for s in args.schedule_topo.split('/'))
            except ValueError:
                raise SystemExit('--schedule-topo must look like '
                                 '"4,4/4,2"; got %r'
                                 % args.schedule_topo)
        else:
            per_node = rs.node_accelerator_devices or \
                {a: [0] for a in rs.nodes}
            slices = (tuple(len(v) for v in per_node.values()),)
        topo = search.ScheduleTopo(slices=slices)
        sbytes = args.schedule_bytes or sum(
            int(np.prod(v.shape or (1,))) * np.dtype(v.dtype).itemsize
            for v in gi.trainable_var_op_to_var.values())
        schedules = (topo, sbytes) + tuple(search.rank_schedules(
            sbytes, 'float32', topo, params,
            staging_budget_bytes=budget))

    def cand_json(feas, infeas):
        return [dict(c.strategy.cost, feasible=True) for c in feas] + \
            [{'builder': c.name, 'feasible': False, 'error': c.error}
             for c in infeas]

    if args.json:
        out = {
            'model': args.model,
            'topology': repr(rs.topology),
            'memory_budget_bytes': budget,
            'candidates': cand_json(feasible, infeasible),
        }
        if flat is not None:
            out['candidates_flat'] = cand_json(*flat)
        if serving is not None:
            out['serving'] = serving
        if schedules is not None:
            topo, sbytes, sf, si = schedules
            out['schedules'] = {
                'topo': [list(s) for s in topo.slices],
                'bucket_bytes': sbytes,
                'candidates': [
                    {'name': c.name, 'rank': c.rank, 'feasible': True,
                     'handwritten': c.handwritten,
                     'predicted_s': c.predicted_s,
                     'per_step_s': list(c.per_step_s),
                     'tier_bytes': c.tier_bytes,
                     'staging_bytes': c.staging_bytes,
                     'verify_s': c.verify_s,
                     'program': c.program.to_dict()} for c in sf] +
                [{'name': c.name, 'feasible': False, 'error': c.error}
                 for c in si],
            }
        print(json.dumps(out))
        return 0
    print('model=%s  vars=%d  %r  replicas=%d%s' % (
        args.model, len(gi.trainable_var_op_to_var), rs.topology,
        feasible[0].report.num_replicas if feasible else 0,
        '  budget=%.1fGB' % args.budget_gb if budget else ''))
    if flat is not None:
        print('-- hierarchical-aware ranking '
              '(two-level where the cost model picks it) --')
    print(search.format_ranked_table(feasible, infeasible))
    if flat is not None:
        print('-- flat-forced ranking (every bucket a flat ring) --')
        print(search.format_ranked_table(*flat))
    if schedules is not None:
        topo, sbytes, sf, si = schedules
        from autodist_tpu.parallel import schedule_ir as sir
        from autodist_tpu.simulator.calibrate import tier_links
        links = tier_links(params)
        if topo.links:
            links.update(topo.links)
        print('-- schedule-IR candidates: %.2f MiB bucket over '
              'slices %s --' % (sbytes / (1 << 20),
                                [list(s) for s in topo.slices]))
        print(search.format_schedule_table(sf, si))
        for c in sf:
            print(sir.format_program(c.program, params, links=links))
    if serving is not None:
        print('serving: %d replica(s) @ %.1f polls/s on the %s wire  '
              'snapshot %.2fMB/pull (%.1fms)  fleet %.2fMB/s '
              '(rows %.2fMB/s)  = %.1f%% of one DCN link'
              % (serving['replicas'], args.serve_poll_hz,
                 serving['wire'],
                 serving['snapshot_wire_bytes'] / 1e6,
                 1e3 * serving['snapshot_pull_s'],
                 serving['serve_bytes_per_s'] / 1e6,
                 serving['row_bytes_per_s'] / 1e6,
                 100.0 * serving['dcn_link_frac']))
    return 0


if __name__ == '__main__':
    sys.exit(main())
