"""Online performance sentry CLI: cohort table + straggler verdicts.

    # live: poll a running cohort's telemetry namespace off the coord
    # service (the chief's in-process CohortMonitor is the twin)
    python tools/monitor.py --addr 127.0.0.1:14998 --ns <strategy id> \\
        --workers 4 [--poll 5 --interval 2.0]

    # offline: span-record batch files (the telemetry.aggregate
    # schema — what trace_view also reads)
    python tools/monitor.py records.json --json

Renders the per-worker rolling statistics (median step wall, work
time, per-phase medians — gate / pull / push / pipeline / compute) and
every active straggler verdict with its phase attribution ("86% of the
excess is gate-wait ⇒ upstream victim, not culprit"). ``--json``
prints the machine-readable monitor snapshot (the same dict
``health_report``'s perf section carries). Exit 0 always (including
when verdicts are active — the sentry observes, scripts decide);
nonzero only on unusable input.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_records(path):
    with open(path) as f:
        payload = json.load(f)
    if not isinstance(payload, list):
        raise ValueError(
            '%s: not a span-record batch list (flight dumps and Chrome '
            'traces belong to tools/trace_view.py)' % path)
    return payload


def main(argv=None):
    from autodist_tpu.telemetry.monitor import (CohortMonitor,
                                                format_snapshot)
    ap = argparse.ArgumentParser(
        description='cohort performance table + straggler verdicts '
                    'from the telemetry plane')
    ap.add_argument('paths', nargs='*',
                    help='span-record batch files (offline mode)')
    ap.add_argument('--addr', help='coord service host:port for live '
                                   'polling')
    ap.add_argument('--ns', help='run namespace (strategy id) for '
                                 'live polling')
    ap.add_argument('--workers', type=int, default=2,
                    help='worker count for live polling')
    ap.add_argument('--poll', type=int, default=1,
                    help='live mode: how many poll rounds')
    ap.add_argument('--interval', type=float, default=2.0,
                    help='live mode: seconds between poll rounds')
    ap.add_argument('--window', type=int, default=None,
                    help='rolling-stat window override '
                         '(AUTODIST_MONITOR_WINDOW)')
    ap.add_argument('--warmup', type=int, default=2,
                    help='steps excluded from baselines as '
                         'compile/warm-up')
    ap.add_argument('--policy', default=None,
                    choices=('off', 'warn', 'advise'),
                    help='verdict policy override '
                         '(AUTODIST_STRAGGLER_POLICY)')
    ap.add_argument('--json', action='store_true',
                    help='print the machine-readable snapshot')
    args = ap.parse_args(argv)

    live = bool(args.addr and args.ns)
    if not live and not args.paths:
        print('monitor: need record files or --addr/--ns',
              file=sys.stderr)
        return 1
    client = None
    if live:
        from autodist_tpu.runtime.coord_client import CoordClient
        host, port = args.addr.rsplit(':', 1)
        client = CoordClient((host, int(port)))
    try:
        # confirmations=1: the chief's in-process monitor uses
        # hysteresis against flapping, but a single-shot CLI
        # inspection has exactly one round — it must not be eaten
        mon = CohortMonitor(
            client=client, ns=args.ns,
            workers=['p%d' % i for i in range(args.workers)],
            window=args.window, warmup_steps=args.warmup,
            confirmations=1, policy=args.policy)
        for path in args.paths:
            mon.ingest(_load_records(path))
        if args.paths:
            mon.update_verdicts()
        if live:
            import time
            for i in range(max(1, args.poll)):
                n = mon.poll()
                if not args.json and args.poll > 1:
                    print('poll %d/%d: %d new record(s)'
                          % (i + 1, args.poll, n))
                if i + 1 < args.poll:
                    time.sleep(args.interval)
        snap = mon.snapshot()
        if args.json:
            print(json.dumps(snap, indent=2, sort_keys=True))
        else:
            print(format_snapshot(snap))
        return 0
    finally:
        if client is not None:
            client.close()


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
