"""Cohort trace assembly + Chrome trace_event export CLI.

    # offline: span-record batches / flight-recorder dumps -> one trace
    python tools/trace_view.py records.json flightrec-*.json \\
        --out trace.json

    # live: collect a running (or just-finished, pre-purge) cohort's
    # pushed telemetry batches off the coord service
    python tools/trace_view.py --addr 127.0.0.1:14998 --ns <strategy id> \\
        --workers 4 --out trace.json

    # machine-readable summary (tier-1 smoke): worker/event counts and
    # the per-step timeline (per-worker step spans aligned on step ids)
    python tools/trace_view.py records.json --json

Inputs are sniffed per file: a flight-recorder dump (``{'events':
[...]}``) contributes instant events on a control-plane lane; a JSON
list is span records (``telemetry.aggregate`` schema); a
``{'traceEvents': ...}`` file is merged as-is. The output opens in
``chrome://tracing`` / Perfetto with one process row per worker
(``Session.export_chrome_trace`` is the in-process twin the chief runs
at close).
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_file(path, records, flight_events, premade):
    with open(path) as f:
        payload = json.load(f)
    if isinstance(payload, list):
        records.extend(payload)
    elif isinstance(payload, dict) and 'events' in payload:
        ctx = payload.get('context', {})
        for ev in payload['events']:
            ev.setdefault('worker_self', ctx.get('worker', 'p0'))
            flight_events.append(ev)
    elif isinstance(payload, dict) and 'traceEvents' in payload:
        premade.extend(payload['traceEvents'])
    else:
        raise ValueError(
            '%s: not a records list, flight-recorder dump or Chrome '
            'trace' % path)


def _collect_live(addr, ns, workers):
    from autodist_tpu.runtime.coord_client import CoordClient
    from autodist_tpu.telemetry import collect_records
    host, port = addr.rsplit(':', 1)
    client = CoordClient((host, int(port)))
    try:
        return collect_records(client, ns,
                               ['p%d' % i for i in range(workers)])
    finally:
        client.close()


def main(argv=None):
    from autodist_tpu.telemetry import chrome_trace, step_timeline
    ap = argparse.ArgumentParser(
        description='assemble cohort telemetry into a Chrome '
                    'trace_event JSON')
    ap.add_argument('paths', nargs='*',
                    help='span-record batches, flight-recorder dumps '
                         'or Chrome traces to merge')
    ap.add_argument('--addr', help='coord service host:port for live '
                                   'collection')
    ap.add_argument('--ns', help='run namespace (strategy id) for '
                                 'live collection')
    ap.add_argument('--workers', type=int, default=2,
                    help='worker count for live collection')
    ap.add_argument('--out', help='write the Chrome trace JSON here')
    ap.add_argument('--json', action='store_true',
                    help='print a machine-readable summary')
    args = ap.parse_args(argv)
    records, flight_events, premade = [], [], []
    for path in args.paths:
        _load_file(path, records, flight_events, premade)
    if args.addr and args.ns:
        records.extend(_collect_live(args.addr, args.ns, args.workers))
    if not (records or flight_events or premade):
        print('trace_view: no input events', file=sys.stderr)
        return 1
    records.sort(key=lambda r: r.get('t0', 0.0))
    trace = chrome_trace(records, flight_events=flight_events)
    trace['traceEvents'].extend(premade)
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(trace, f)
    timeline = step_timeline(records)
    workers = sorted({r.get('worker', 'p0') for r in records})
    # per-phase aggregate columns (gate/pull/push/pipeline/compute
    # medians per worker) through the SAME phase-split helper the
    # monitor's verdicts use — one implementation, pinned by a shared
    # test, so the CLI and the verdicts cannot drift
    from autodist_tpu.telemetry.monitor import phase_medians
    phases = phase_medians(records)
    summary = {
        'workers': workers,
        'span_records': len(records),
        'flight_events': len(flight_events),
        'trace_events': len(trace['traceEvents']),
        'steps': {str(s): timeline[s] for s in sorted(timeline)},
        'phases': phases,
        'out': args.out or None,
    }
    if args.json:
        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        print('workers: %s' % ', '.join(workers))
        print('%d span records, %d flight events -> %d trace events%s'
              % (len(records), len(flight_events),
                 len(trace['traceEvents']),
                 ' -> %s' % args.out if args.out else ''))
        for s in sorted(timeline):
            row = '  step %-4d ' % s + '  '.join(
                '%s %.1fms' % (w, dt * 1e3)
                for w, dt in sorted(timeline[s].items()))
            print(row)
        for w in sorted(phases):
            agg = phases[w]
            row = '  %s medians:' % w + ''.join(
                '  %s %.1fms' % (p, 1e3 * agg[p])
                for p in ('step', 'gate', 'pull', 'push', 'pipeline',
                          'compute') if p in agg)
            print(row)
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
