"""BERT-large phase-1 remat-policy sweep (round 5 frontier probe).

The 47.5%-MFU point uses FULL per-block remat; round 4's per-op
profile attributed ~9% of the step to scan-stacking bookkeeping plus
the full recompute. This sweeps the selective policies ('dots' keeps
every matmul output — recompute only elementwise work) against full
remat and no remat at phase-1 and phase-2 shapes. OOM rows are
recorded as such.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import bench as B


def main():
    from autodist_tpu.utils.jax_env import apply_jax_env_overrides
    apply_jax_env_overrides()

    import jax
    import jax.numpy as jnp

    from autodist_tpu.models.transformer import (TransformerConfig,
                                                 TransformerLM)

    peak = B.peak_flops_for(jax.devices()[0])
    rng = np.random.RandomState(0)
    steps = 8
    cases = [(128, 512), (128, 384), (512, 96)]
    if len(sys.argv) == 3:        # usage: bert_remat_sweep.py SEQ BATCH
        cases = [(int(sys.argv[1]), int(sys.argv[2]))]
    elif len(sys.argv) != 1:
        sys.exit('usage: bert_remat_sweep.py [SEQ BATCH]')
    for seq, bs in cases:
        for remat in (True, 'dots', False):
            cfg = TransformerConfig.bert_large(dtype=jnp.bfloat16,
                                               remat=remat)
            batch = {'tokens': rng.randint(0, cfg.vocab, (bs, seq),
                                           dtype=np.int32),
                     'targets': rng.randint(0, cfg.vocab, (bs, seq),
                                            dtype=np.int32)}
            label = 's%d_B%d_remat-%s' % (seq, bs, remat)
            try:
                stats = {}
                dt, _ = B.run_workload(TransformerLM(cfg), batch,
                                       steps=steps, stats_out=stats)
                tps = bs * seq * steps / dt
                print(label, json.dumps(
                    {'tokens_per_s_chip': round(tps, 1),
                     'mfu_pct': B.mfu_pct(
                         tps * B.bert_train_flops_per_token(cfg, seq),
                         peak),
                     'dispersion_pct': stats['dispersion_pct']}),
                    flush=True)
            except Exception as e:   # noqa: BLE001 - OOM rows recorded
                print(label, json.dumps({'error': str(e)[:160]}),
                      flush=True)


if __name__ == '__main__':
    main()
