"""BERT-large phase-2 (seq 512) sweep on the chip (VERDICT r4 item 2).

Sweeps per-chip batch and the flash-attention kernel (force-on vs the
auto XLA path — seq 512 sits at the kernel's measured 1.0x crossover)
at bert_large's own example default sequence length. Reports tokens/s
per chip + analytic MFU per config, median of 3 fenced blocks.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import bench as B


def main():
    from autodist_tpu.utils.jax_env import apply_jax_env_overrides
    apply_jax_env_overrides()

    import jax
    import jax.numpy as jnp

    from autodist_tpu.kernels import flash_attention as fa
    from autodist_tpu.models.transformer import (TransformerConfig,
                                                 TransformerLM)

    dev = jax.devices()[0]
    peak = B.peak_flops_for(dev)
    seq = 512
    cfg = TransformerConfig.bert_large(dtype=jnp.bfloat16, remat=True)
    rng = np.random.RandomState(0)
    flops_tok = B.bert_train_flops_per_token(cfg, seq)
    auto_min = fa.MIN_KERNEL_SEQ

    batches = [int(b) for b in
               (sys.argv[1:] or ['64', '96', '128'])]
    force_off = 10 ** 9   # the xla-attn arm must DISABLE the kernel
                          # regardless of the adopted default threshold
    for batch_size in batches:
        batch = {'tokens': rng.randint(0, cfg.vocab, (batch_size, seq),
                                       dtype=np.int32),
                 'targets': rng.randint(0, cfg.vocab, (batch_size, seq),
                                        dtype=np.int32)}
        for flash in (False, True):
            fa.MIN_KERNEL_SEQ = 512 if flash else force_off
            label = 'B%d_%s' % (batch_size,
                                'flash' if flash else 'xla-attn')
            try:
                stats = {}
                dt, _ = B.run_workload(TransformerLM(cfg), batch,
                                       steps=8, stats_out=stats)
                tps = batch_size * seq * 8 / dt
                print(label, json.dumps(
                    {'tokens_per_s_chip': round(tps, 1),
                     'mfu_pct': B.mfu_pct(tps * flops_tok, peak),
                     'dispersion_pct': stats['dispersion_pct']}),
                    flush=True)
            except Exception as e:   # noqa: BLE001 - OOM rows recorded
                print(label, json.dumps({'error': str(e)[:200]}),
                      flush=True)
    fa.MIN_KERNEL_SEQ = auto_min


if __name__ == '__main__':
    main()
