"""Compressor wire-pricing drift check.

Asserts that the cost model's ``_WIRE_ITEMSIZE`` table covers the
compressor registry in ``autodist_tpu/parallel/compressor.py`` exactly.
A compressor registered but missing from the table would silently price
as f32 (``wire_bytes`` falls back to the raw itemsize), so the
simulator could never rank the tier the compressor exists to enable —
the same failure mode the protocol-drift check (check_protocol.py)
guards against on the native wire.

Run:  python tools/check_wire_pricing.py      (exit 0 = in sync)
Wired into tier-1 via tests/test_quantized_wire.py.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def find_drift():
    """Returns a list of human-readable drift problems (empty = in
    sync)."""
    from autodist_tpu.parallel.compressor import _REGISTRY
    from autodist_tpu.simulator.cost_model import _WIRE_ITEMSIZE
    registry = set(_REGISTRY)
    priced = set(_WIRE_ITEMSIZE)
    problems = []
    for name in sorted(registry - priced):
        problems.append('compressor registered but missing from '
                        'cost_model._WIRE_ITEMSIZE (would silently '
                        'price as f32): %s' % name)
    for name in sorted(priced - registry):
        problems.append('priced in cost_model._WIRE_ITEMSIZE but not '
                        'in the compressor registry (stale entry): %s'
                        % name)
    if not registry:
        problems.append('compressor registry is empty — the registry '
                        'moved or the import graph broke')
    return problems


def main(argv=None):
    problems = find_drift()
    if problems:
        print('compressor wire-pricing drift:')
        for p in problems:
            print('  - ' + p)
        return 1
    from autodist_tpu.parallel.compressor import _REGISTRY
    print('cost_model._WIRE_ITEMSIZE and the compressor registry '
          'agree (%d compressors)' % len(_REGISTRY))
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
