"""Compressor wire-pricing drift check — compatibility shim.

The check lives in :mod:`autodist_tpu.analysis.schedule_lint` now
(PR 9 folded it into the static-analysis subsystem alongside the
emission-predicate and reshard-algebra checks); this entry point keeps
the documented ``python tools/check_wire_pricing.py`` invocation
working and re-exports ``find_drift``. Prefer
``python tools/analyze.py --schedule``.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def find_drift():
    from autodist_tpu.analysis.schedule_lint import check_wire_pricing
    return check_wire_pricing()


def main(argv=None):
    problems = find_drift()
    if problems:
        print('compressor wire-pricing drift:')
        for p in problems:
            print('  - ' + p)
        return 1
    from autodist_tpu.parallel.compressor import _REGISTRY
    print('cost_model._WIRE_ITEMSIZE and the compressor registry '
          'agree (%d compressors)' % len(_REGISTRY))
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
