"""Roofline observatory CLI — render MFU/regime, HBM drift and the
per-entry collective drift table from records or traces.

    # a BENCH record (driver wrapper or bench.py's raw line)
    python tools/roofline.py BENCH_r06.json

    # a raw roofline block (bench extra.roofline, or your own)
    python tools/roofline.py roofline.json --json

    # offline join: a profiler trace dir + the static schedule it ran
    # (JSON list of static_collective_schedule entries)
    python tools/roofline.py /tmp/trace --schedule sched.json \\
        --replicas 8

Inputs are sniffed per path: a JSON file carrying a ``roofline`` block
(BENCH record, wrapped or raw) or BEING one (a dict with ``drift`` /
``mfu`` keys) renders directly; a directory is treated as a captured
profiler trace whose collective timeline is joined against
``--schedule`` through the SAME ``telemetry.roofline.drift_table``
join the bench uses. ``--json`` prints the machine-readable summary
(the tier-1 subprocess smoke's contract).
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault('JAX_PLATFORMS', 'cpu')


def _load_block(path):
    """A JSON file -> its roofline block, or None when the file is
    JSON but carries none."""
    with open(path) as f:
        payload = json.load(f)
    if isinstance(payload, dict) and isinstance(
            payload.get('parsed'), dict):
        payload = payload['parsed']
    if isinstance(payload, dict):
        block = (payload.get('extra') or {}).get('roofline')
        if isinstance(block, dict):
            return block
        if 'drift' in payload or 'mfu' in payload:
            return payload
    return None


def _render(block, as_json):
    from autodist_tpu.telemetry.roofline import format_drift_table
    if as_json:
        print(json.dumps(block, indent=2, sort_keys=True,
                         default=str))
        return
    mfu = block.get('mfu')
    if mfu is not None:
        print('MFU %.2f%%  regime=%s  hbm_frac=%s'
              % (100.0 * mfu, block.get('roofline_regime'),
                 block.get('hbm_frac')))
    else:
        print('MFU: null (%s)'
              % block.get('mfu_null_reason', 'no reason recorded'))
    mem = block.get('memory') or {}
    if mem.get('available'):
        print('HBM drift: measured %.1f MiB vs estimated %.1f MiB '
              '(ratio %s)'
              % (mem.get('measured_total_bytes', 0) / (1 << 20),
                 mem.get('estimated_total_bytes', 0) / (1 << 20),
                 mem.get('drift_ratio')))
        for cls, rec in sorted((mem.get('classes') or {}).items()):
            print('  %-10s measured %.1f MiB vs estimated %.1f MiB '
                  '(ratio %s)'
                  % (cls, rec['measured_bytes'] / (1 << 20),
                     rec['estimated_bytes'] / (1 << 20),
                     rec['drift_ratio']))
    elif mem:
        print('HBM drift: unavailable (%s)' % mem.get('reason'))
    drift = block.get('drift') or {}
    if drift.get('entries'):
        print(format_drift_table(drift))
        if 'entry_ids_roundtrip' in drift:
            print('entry ids round-trip to the static schedule: %s'
                  % drift['entry_ids_roundtrip'])


def _join_trace(trace_dir, schedule_path, replicas, multi_node):
    from autodist_tpu.simulator.calibrate import calibrate_from_drift
    from autodist_tpu.simulator.cost_model import CostModelParams
    from autodist_tpu.telemetry.roofline import drift_table
    from autodist_tpu.utils.profiling import collective_timeline
    with open(schedule_path) as f:
        schedule = json.load(f)
    if not isinstance(schedule, list):
        raise ValueError('%s: not a schedule entry list'
                         % schedule_path)
    timeline = collective_timeline(
        trace_dir, expected_collectives=len(schedule))
    table = drift_table(schedule, timeline, replicas,
                        params=CostModelParams(),
                        multi_node=multi_node)
    refit = calibrate_from_drift(CostModelParams(), table, replicas)
    return {'drift': {k: v for k, v in table.items()
                      if k != 'samples'},
            'calibration': {'calibrated': bool(refit.calibrated),
                            'alpha_ici_s': refit.alpha_ici_s,
                            'beta_ici_s_per_byte':
                                refit.beta_ici_s_per_byte,
                            'alpha_dcn_s': refit.alpha_dcn_s,
                            'beta_dcn_s_per_byte':
                                refit.beta_dcn_s_per_byte}}


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='render roofline records / join a trace against '
                    'its static collective schedule')
    ap.add_argument('paths', nargs='+',
                    help='BENCH records, roofline blocks, or a '
                         'profiler trace dir (with --schedule)')
    ap.add_argument('--schedule',
                    help='static_collective_schedule entries (JSON '
                         'list) for trace-dir inputs')
    ap.add_argument('--replicas', type=int, default=2,
                    help='replica count a trace-dir join prices '
                         'against (default 2)')
    ap.add_argument('--multi-node', action='store_true',
                    help='price flat entries on the DCN tier')
    ap.add_argument('--json', action='store_true',
                    help='print machine-readable blocks')
    args = ap.parse_args(argv)
    rendered = 0
    for path in args.paths:
        if os.path.isdir(path):
            if not args.schedule:
                print('roofline: %s is a trace dir — pass --schedule '
                      'with its static collective schedule' % path,
                      file=sys.stderr)
                return 2
            block = _join_trace(path, args.schedule, args.replicas,
                                args.multi_node)
        else:
            block = _load_block(path)
            if block is None:
                print('roofline: %s carries no roofline block'
                      % path, file=sys.stderr)
                continue
        if rendered and not args.json:
            print('-' * 60)
        _render(block, args.json)
        rendered += 1
    if not rendered:
        print('roofline: no renderable input', file=sys.stderr)
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
