"""CNN-family per-chip batch sweep (round 5: the batch landscape is
non-monotonic — sweep DOWN as well as up)."""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import bench as B


def main():
    from autodist_tpu.utils.jax_env import apply_jax_env_overrides
    apply_jax_env_overrides()

    import jax.numpy as jnp
    import optax

    from autodist_tpu.models import vision

    name = sys.argv[1]
    batches = [int(b) for b in sys.argv[2:]]
    builders = {
        'resnet101': (lambda: vision.ResNet.resnet101(dtype=jnp.bfloat16),
                      224),
        'densenet121': (lambda: vision.DenseNet.densenet121(
            dtype=jnp.bfloat16), 224),
        'inceptionv3': (lambda: vision.InceptionV3(dtype=jnp.bfloat16),
                        299),
        'vgg16': (lambda: vision.VGG.vgg16(dtype=jnp.bfloat16), 224),
    }
    fn, hw = builders[name]
    lr = 0.001 if name == 'vgg16' else 0.1   # no-BN net: keep SGD cool
    rng = np.random.RandomState(0)
    steps = 10
    for bs in batches:
        batch = {'images': rng.rand(bs, hw, hw, 3).astype('f4'),
                 'labels': rng.randint(0, 10, (bs,), dtype=np.int32)}
        try:
            stats = {}
            dt, _ = B.run_workload(fn(), batch, steps,
                                   optimizer=optax.sgd(lr, momentum=0.9),
                                   stats_out=stats)
            print('%s_B%d' % (name, bs), json.dumps(
                {'img_per_s': round(bs * steps / dt, 1),
                 'step_ms': round(1000 * dt / steps, 2),
                 'dispersion_pct': stats['dispersion_pct']}), flush=True)
        except Exception as e:   # noqa: BLE001 - OOM rows recorded
            print('%s_B%d' % (name, bs),
                  json.dumps({'error': str(e)[:120]}), flush=True)


if __name__ == '__main__':
    main()
